"""Asynchronous shard_map pipeline engine tests (VERDICT r2 item 4).

Covers: numeric equivalence against the sequential ground truth (even and
uneven stage plans), the stage-resident vocab-sharded boundary layers,
real-branch structure in the lowered program, and dropout reproducibility.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.models.gpt import (
    gpt_loss, make_gpt_smap_grad_fn)


def _setup(M=4, S=2, num_layers=4, dropout=0.0, **kw):
  env = epl.init()
  mesh = env.cluster.build_mesh(stage=S)
  base = dict(vocab_size=64, num_layers=num_layers, num_heads=4,
              d_model=32, d_ff=64, max_seq_len=16, dtype=jnp.float32,
              pipeline_stages=S, num_micro_batch=M, dropout_rate=dropout)
  base.update(kw)
  pp = GPT(GPTConfig(**base))
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4 * M, 17)),
                    jnp.int32)
  params = pp.init(jax.random.PRNGKey(0), ids[:, :-1])["params"]
  return mesh, pp, base, ids, params


@pytest.mark.parametrize("S,M", [(2, 4), (2, 1), (4, 6)])
@pytest.mark.slow
def test_smap_gpt_matches_sequential(S, M):
  """smap-engine loss and gradients == autodiff through the sequential
  ground truth (same boxed params as every other pipeline path)."""
  mesh, pp, base, ids, params = _setup(M=M, S=S)
  seq = GPT(GPTConfig(**base, pipeline_debug_sequential=True))

  grad_smap = make_gpt_smap_grad_fn(pp, mesh)
  (l1, _), g1 = jax.jit(lambda p: grad_smap(p, {"ids": ids}, None))(params)

  def seq_loss(p):
    return gpt_loss(seq, p, {"ids": ids})[0]

  l2, g2 = jax.jit(jax.value_and_grad(seq_loss))(params)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=5e-3, atol=1e-5),
      g1, g2)


@pytest.mark.slow
def test_smap_gpt_uneven_stages_match_sequential():
  """5 layers over 2 stages: the masked slot is a real lax.cond branch
  per device, and numerics still match the sequential ground truth."""
  mesh, pp, base, ids, params = _setup(M=4, S=2, num_layers=5)
  seq = GPT(GPTConfig(**base, pipeline_debug_sequential=True))

  grad_smap = make_gpt_smap_grad_fn(pp, mesh)
  (l1, _), g1 = jax.jit(lambda p: grad_smap(p, {"ids": ids}, None))(params)
  l2, g2 = jax.jit(jax.value_and_grad(
      lambda p: gpt_loss(seq, p, {"ids": ids})[0]))(params)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=5e-3, atol=1e-5),
      g1, g2)


def test_smap_lowered_program_structure():
  """The lowered program carries the engine's signature moves: explicit
  collective-permute stage boundaries and real conditionals (the vmapped
  engines lower masked slots to selects — no conditional survives)."""
  mesh, pp, base, ids, params = _setup(M=4, S=2)
  grad_smap = make_gpt_smap_grad_fn(pp, mesh)
  text = jax.jit(
      lambda p: grad_smap(p, {"ids": ids}, None)).lower(params).as_text()
  assert "collective-permute" in text or "collective_permute" in text
  assert "conditional" in text or "case" in text


def test_smap_boundary_params_stage_sharded():
  """The tied table's gradient comes back whole (global [V, D]) but the
  engine's in-spec shards it over the stage axis — per-device slice is
  [V/S, D], the S-fold stage-resident memory saving."""
  from easyparallellibrary_tpu.parallel.pipeline_smap import (
      _stage_psum_specs)
  from jax.sharding import PartitionSpec as P
  from easyparallellibrary_tpu import constants

  mesh, pp, base, ids, params = _setup(M=2, S=2)
  grad_smap = make_gpt_smap_grad_fn(pp, mesh)
  (_, _), g = jax.jit(lambda p: grad_smap(p, {"ids": ids}, None))(params)
  wte = g["wte"]["embedding"]
  wte = wte.value if hasattr(wte, "value") else wte
  assert wte.shape == (64, 32)
  # Stage-replicated leaves (wpe, ln_f) are flagged for stage-psum; the
  # vocab-sharded table is not.
  specs = {"a": P(constants.STAGE_AXIS, None), "b": P()}
  flags = _stage_psum_specs(specs)
  assert flags == {"a": False, "b": True}


def test_smap_vocab_not_divisible_raises():
  mesh, pp, base, ids, params = _setup(M=2, S=2, vocab_size=63)
  with pytest.raises(ValueError, match="divide"):
    make_gpt_smap_grad_fn(pp, mesh)


def test_smap_dropout_reproducible():
  mesh, pp, base, ids, params = _setup(M=4, S=2, dropout=0.2)
  grad_fn = make_gpt_smap_grad_fn(pp, mesh)
  f = jax.jit(lambda p, r: grad_fn(p, {"ids": ids}, r))
  (l_a, _), g_a = f(params, jax.random.PRNGKey(1))
  (l_b, _), _ = f(params, jax.random.PRNGKey(2))
  (l_a2, _), _ = f(params, jax.random.PRNGKey(1))
  assert float(l_a) != float(l_b)
  np.testing.assert_allclose(float(l_a), float(l_a2), rtol=1e-6)
  finite = jax.tree_util.tree_map(
      lambda g: bool(jnp.all(jnp.isfinite(g.value
                                          if hasattr(g, "value") else g))),
      g_a)
  assert all(jax.tree_util.tree_leaves(finite))


def test_smap_share_scaling():
  """Documents the transpose semantics the engine's 1/S share scaling
  rests on: inside shard_map, psum transposes to psum of cotangents, so
  a loss seeded identically on every device overcounts sharded-leaf
  grads by S — dividing each device's objective by S restores 1x."""
  from jax.sharding import Mesh, PartitionSpec as P

  mesh = Mesh(np.array(jax.devices()[:2]), ("stage",))

  def body(w_loc, b):
    s = jax.lax.axis_index("stage")

    def loss(w_loc, b):
      part = w_loc[0] * (b * 2.0) * (s + 1.0)
      z = jax.lax.psum(part, "stage")
      return z * 3.0 / 2.0          # the 1/S share

    g = jax.grad(loss, argnums=(0, 1))(w_loc, b)
    return (g[0], jax.lax.psum(g[1], "stage")[None])

  f = jax.shard_map(body, mesh=mesh, in_specs=(P("stage"), P()),
                    out_specs=(P("stage"), P("stage")), check_vma=False)
  gw, gb = jax.jit(f)(jnp.ones((2,)), jnp.ones(()))
  # true grads of L = 6*(w0 + 2*w1)*b at b=1: dw = [6, 12], db = 18.
  np.testing.assert_allclose(np.asarray(gw), [6.0, 12.0])
  np.testing.assert_allclose(np.asarray(gb), [18.0, 18.0])


@pytest.mark.parametrize("S,M", [(2, 4), (4, 6), (2, 1)])
@pytest.mark.slow
def test_smap_1f1b_matches_sequential(S, M):
  """The manual per-device 1F1B wavefront == sequential autodiff."""
  mesh, pp, base, ids, params = _setup(M=M, S=S)
  seq = GPT(GPTConfig(**base, pipeline_debug_sequential=True))

  grad_fn = make_gpt_smap_grad_fn(pp, mesh, schedule="1f1b")
  (l1, _), g1 = jax.jit(lambda p: grad_fn(p, {"ids": ids}, None))(params)
  l2, g2 = jax.jit(jax.value_and_grad(
      lambda p: gpt_loss(seq, p, {"ids": ids})[0]))(params)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=5e-3, atol=1e-5),
      g1, g2)


@pytest.mark.slow
def test_smap_1f1b_uneven_stages():
  mesh, pp, base, ids, params = _setup(M=4, S=2, num_layers=5)
  seq = GPT(GPTConfig(**base, pipeline_debug_sequential=True))
  grad_fn = make_gpt_smap_grad_fn(pp, mesh, schedule="1f1b")
  (l1, _), g1 = jax.jit(lambda p: grad_fn(p, {"ids": ids}, None))(params)
  l2, g2 = jax.jit(jax.value_and_grad(
      lambda p: gpt_loss(seq, p, {"ids": ids})[0]))(params)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=5e-3, atol=1e-5),
      g1, g2)


@pytest.mark.slow
def test_smap_1f1b_bounds_temp_bytes_vs_gpipe():
  """The residual ring bounds live activations: at M=8, S=4 the 1F1B
  wavefront's compiled temp bytes undercut the GPipe-order autodiff
  engine (the smap twin of
  test_schedule_1f1b.test_1f1b_bounds_live_activations_vs_gpipe)."""
  mesh, pp, base, ids, params = _setup(M=8, S=4, num_layers=4)

  def temp_bytes(schedule):
    g = make_gpt_smap_grad_fn(pp, mesh, schedule=schedule)
    lowered = jax.jit(lambda p: g(p, {"ids": ids}, None)).lower(params)
    return lowered.compile().memory_analysis().temp_size_in_bytes

  b_1f1b = temp_bytes("1f1b")
  b_gpipe = temp_bytes("gpipe")
  assert b_1f1b < b_gpipe, (b_1f1b, b_gpipe)


def test_smap_1f1b_loss_scale_seeding():
  """AMP parity: a loss_scale seed returns unscaled grads (identical to
  the unseeded run) — matching one_f_one_b's contract."""
  mesh, pp, base, ids, params = _setup(M=4, S=2)
  grad_fn = make_gpt_smap_grad_fn(pp, mesh, schedule="1f1b")
  (l1, _), g1 = jax.jit(
      lambda p: grad_fn(p, {"ids": ids}, None))(params)
  (l2, _), g2 = jax.jit(
      lambda p: grad_fn(p, {"ids": ids}, None, 128.0))(params)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=1e-4, atol=1e-6),
      g1, g2)


def test_smap_config_engine_dispatch():
  """VERDICT r3 item 2: `pipeline.engine="smap"` selects the shard_map
  engine through `make_gpt_train_step` — config only, no direct engine
  call — and the tied table is COMMITTED stage-resident ([V/S, D] per
  stage group), the argument-bytes saving the round-3 benchmark measured
  (reference analog: the scheduler-registry dispatch,
  epl/strategies/scheduler.py:120-131)."""
  import optax
  from easyparallellibrary_tpu.models.gpt import make_gpt_train_step
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state, parallelize)

  env = epl.init(epl.Config({"pipeline.engine": "smap"}))
  cfg = GPTConfig(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
                  d_ff=64, max_seq_len=16, dtype=jnp.float32,
                  pipeline_stages=2, num_micro_batch=4)
  with epl.replicate(1):
    model = GPT(cfg)
  mesh = env.cluster.build_mesh(stage=2)
  # 4 micro-batches x data axis (4) x 1 sample.
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (16, 17)),
                    jnp.int32)

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, ids[:, :-1])["params"],
                             tx=optax.adam(1e-2))

  state, shardings = create_sharded_train_state(init_fn, mesh,
                                                jax.random.PRNGKey(0))
  wte = state.params["wte"]["embedding"]
  leaf = wte.value if hasattr(wte, "value") else wte
  assert leaf.sharding.shard_shape(leaf.shape)[0] == leaf.shape[0] // 2

  step = parallelize(make_gpt_train_step(model), mesh, shardings)
  losses = []
  for i in range(4):
    state, m = step(state, {"ids": ids}, jax.random.PRNGKey(i))
    losses.append(float(m["loss"]))
  assert all(np.isfinite(l) for l in losses)
  assert losses[-1] < losses[0]


def test_smap_tp_hybrid_matches_sequential():
  """VERDICT r3 item 2(c): tensor parallelism composes inside the smap
  stage program (partial-manual shard_map leaves the model axis to
  GSPMD) — loss and grads match the sequential ground truth on a
  stage2 x model2 mesh."""
  env = epl.init()
  mesh = env.cluster.build_mesh(stage=2, model=2)
  base = dict(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
              d_ff=64, max_seq_len=16, dtype=jnp.float32,
              tensor_parallel=True, pipeline_stages=2, num_micro_batch=4)
  pp = GPT(GPTConfig(**base))
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 17)),
                    jnp.int32)
  params = pp.init(jax.random.PRNGKey(0), ids[:, :-1])["params"]
  seq = GPT(GPTConfig(**base, pipeline_debug_sequential=True))

  grad_smap = make_gpt_smap_grad_fn(pp, mesh)
  (l1, _), g1 = jax.jit(lambda p: grad_smap(p, {"ids": ids}, None))(params)
  l2, g2 = jax.jit(jax.value_and_grad(
      lambda p: gpt_loss(seq, p, {"ids": ids})[0]))(params)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=5e-3, atol=1e-5),
      g1, g2)


def test_smap_untied_embeddings_match_sequential():
  """VERDICT r3 item 2(c): untied embeddings compose — the LM head
  kernel is stage-vocab-sharded ([D, V/S] per stage) like the tied
  table, and numerics match the sequential ground truth."""
  mesh, pp, base, ids, params = _setup(M=4, S=2, tie_embeddings=False)
  seq = GPT(GPTConfig(**base, pipeline_debug_sequential=True))

  grad_smap = make_gpt_smap_grad_fn(pp, mesh)
  (l1, _), g1 = jax.jit(lambda p: grad_smap(p, {"ids": ids}, None))(params)
  l2, g2 = jax.jit(jax.value_and_grad(
      lambda p: gpt_loss(seq, p, {"ids": ids})[0]))(params)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=5e-3, atol=1e-5),
      g1, g2)


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
def test_smap_moe_matches_vmap_1f1b(schedule):
  """MoE composes on the smap engine (constraint lifted in round 4):
  loss (incl. the weighted load-balancing aux) and grads match the
  vmapped 1F1B engine, which shares the per-micro-batch aux semantics
  (a sequential full-batch reference would differ in the aux term —
  mean-of-products vs product-of-means)."""
  from easyparallellibrary_tpu.models.gpt import make_gpt_1f1b_grad_fn

  env = epl.init()
  # data axis size 1: the smap engine routes MoE per data shard while the
  # vmapped engine routes over the global micro-batch — identical only
  # when there is one data shard (the aux statistics are means over the
  # tokens each router instance sees).
  mesh = env.cluster.build_mesh(stage=4, expert=2)
  cfg = GPTConfig(vocab_size=64, num_layers=8, num_heads=2, d_model=16,
                  d_ff=32, max_seq_len=8, dtype=jnp.float32,
                  pipeline_stages=4, num_micro_batch=4,
                  num_experts=4, moe_every=2, capacity_factor=8.0)
  pp = GPT(cfg)
  dp = mesh.devices.shape[list(mesh.axis_names).index("data")]
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4 * dp, 9)),
                    jnp.int32)
  params = pp.init(jax.random.PRNGKey(0), ids[:, :-1])["params"]

  g_smap = make_gpt_smap_grad_fn(pp, mesh, schedule=schedule)
  (l1, m1), g1 = jax.jit(lambda p: g_smap(p, {"ids": ids}, None))(params)
  g_vmap = make_gpt_1f1b_grad_fn(pp)
  (l2, m2), g2 = jax.jit(lambda p: g_vmap(p, {"ids": ids}, None))(params)

  np.testing.assert_allclose(float(l1), float(l2), rtol=2e-5)
  assert "moe_aux_loss" in m1
  np.testing.assert_allclose(float(m1["moe_aux_loss"]),
                             float(m2["moe_aux_loss"]), rtol=1e-4)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=5e-3, atol=1e-5),
      g1, g2)


def test_smap_moe_interleaved_trains():
  """MoE x interleaved 1F1B (K=2 virtual chunks) trains through the
  config-dispatched path with finite decreasing loss."""
  import optax
  from easyparallellibrary_tpu.models.gpt import make_gpt_train_step
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state, parallelize)

  env = epl.init(epl.Config({"pipeline.engine": "smap"}))
  cfg = GPTConfig(vocab_size=64, num_layers=8, num_heads=2, d_model=16,
                  d_ff=32, max_seq_len=8, dtype=jnp.float32,
                  pipeline_stages=2, num_micro_batch=4,
                  pipeline_interleave=2,
                  num_experts=2, moe_every=2, capacity_factor=4.0)
  with epl.replicate(1):
    model = GPT(cfg)
  mesh = env.cluster.build_mesh(stage=2, expert=2)
  dp = mesh.devices.shape[list(mesh.axis_names).index("data")]
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4 * dp, 9)),
                    jnp.int32)

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, ids[:, :-1])["params"],
                             tx=optax.adam(1e-2))

  state, shardings = create_sharded_train_state(init_fn, mesh,
                                                jax.random.PRNGKey(0))
  step = parallelize(make_gpt_train_step(model), mesh, shardings)
  losses = []
  for i in range(4):
    state, m = step(state, {"ids": ids}, jax.random.PRNGKey(i))
    losses.append(float(m["loss"]))
  assert all(np.isfinite(l) for l in losses)
  assert losses[-1] < losses[0]


def test_smap_moe_a2a_matches_einsum():
  """moe_impl='a2a' inside the smap engine (VERDICT r4 item 4): the
  nested expert shard_map's all-to-alls are safe because the engine
  runs stage compute branch-uniformly for this composition — loss,
  grads and aux must match the einsum path exactly (ample capacity)."""
  base = dict(vocab_size=64, num_layers=8, num_heads=2, d_model=16,
              d_ff=32, max_seq_len=8, dtype=jnp.float32,
              pipeline_stages=2, num_micro_batch=4,
              num_experts=4, moe_every=2, capacity_factor=8.0)

  def run(impl):
    env = epl.init()
    mesh = env.cluster.build_mesh(stage=2, expert=2)
    cfg = GPTConfig(**base, moe_impl=impl)
    pp = GPT(cfg)
    dp = mesh.devices.shape[list(mesh.axis_names).index("data")]
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64,
                                                       (4 * dp, 9)),
                      jnp.int32)
    params = pp.init(jax.random.PRNGKey(0), ids[:, :-1])["params"]
    g_fn = make_gpt_smap_grad_fn(pp, mesh)
    (l, m), g = jax.jit(lambda p: g_fn(p, {"ids": ids}, None))(params)
    return float(l), float(m["moe_aux_loss"]), g

  l_a, aux_a, g_a = run("a2a")
  l_e, aux_e, g_e = run("einsum")
  np.testing.assert_allclose(l_a, l_e, rtol=2e-5)
  np.testing.assert_allclose(aux_a, aux_e, rtol=1e-4)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=5e-3, atol=1e-5),
      g_a, g_e)


def test_smap_zero_v0_trains():
  """ZeRO-v0 (GSPMD optimizer-state sharding) composes with the
  config-dispatched smap engine — it is a state-layout decision,
  engine-independent."""
  import optax
  from easyparallellibrary_tpu.models.gpt import make_gpt_train_step
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state, parallelize)

  env = epl.init(epl.Config({"pipeline.engine": "smap",
                             "zero.level": "v0"}))
  cfg = GPTConfig(vocab_size=64, num_layers=4, num_heads=2, d_model=16,
                  d_ff=32, max_seq_len=8, dtype=jnp.float32,
                  pipeline_stages=2, num_micro_batch=4)
  with epl.replicate(1):
    model = GPT(cfg)
  mesh = env.cluster.build_mesh(stage=2)
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (16, 9)),
                    jnp.int32)

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, ids[:, :-1])["params"],
                             tx=optax.adamw(1e-2))

  state, sh = create_sharded_train_state(init_fn, mesh,
                                         jax.random.PRNGKey(0))
  step = parallelize(make_gpt_train_step(model), mesh, sh)
  losses = []
  for i in range(3):
    state, m = step(state, {"ids": ids}, jax.random.PRNGKey(i))
    losses.append(float(m["loss"]))
  assert all(np.isfinite(l) for l in losses) and losses[-1] < losses[0]


def test_smap_sequence_parallel_guards():
  """The compositions that remain unsafe refuse with named errors: the
  einsum ring is a global-array program (cannot run on the seq-manual
  engine's local shards), and a NESTED shard_map without the seq axis
  still deadlocks (channels span all devices) so the ring refuses to
  nest.  (Ring/Ulysses themselves now compose — test_smap_sequence.py.)"""
  env = epl.init(epl.Config({"sequence.parallelism": "ring",
                             "sequence.axis_size": 2,
                             "sequence.ring_impl": "einsum"}))
  mesh = env.cluster.build_mesh(stage=2, seq=2)
  cfg = GPTConfig(vocab_size=64, num_layers=4, num_heads=2, d_model=16,
                  d_ff=32, max_seq_len=16, dtype=jnp.float32,
                  pipeline_stages=2, num_micro_batch=2,
                  seq_parallel=True, attn_impl="ring")
  with pytest.raises(ValueError, match="global-array"):
    make_gpt_smap_grad_fn(GPT(cfg), mesh)

  # The ring itself refuses to NEST inside a manual region that is not
  # manual over seq (a nested map's collective channels span all
  # devices).
  env = epl.init(epl.Config({"sequence.parallelism": "ring",
                             "sequence.axis_size": 2}))
  mesh = env.cluster.build_mesh(stage=2, seq=2)
  from easyparallellibrary_tpu.sequence import ring_attention
  from jax.sharding import PartitionSpec as P

  def body(q, k, v):
    return ring_attention(q, k, v, causal=True)

  q = jnp.ones((2, 16, 2, 8), jnp.float32)
  mapped = jax.shard_map(body, mesh=mesh,
                         in_specs=(P("stage"),) * 3,
                         out_specs=P("stage"),
                         axis_names=frozenset({"stage"}),
                         check_vma=False)
  with pytest.raises(ValueError, match="manual"):
    jax.jit(mapped)(q, q, q)
