"""Event-driven front door (ISSUE 19): reactor router core + streaming
HTTP/SSE surface with backpressure and cancel-on-disconnect.

The acceptance contract (`make chaos-frontdoor`):

* the reactor (serving/reactor.py) is BIT-EXACT with the sweep — an
  in-process N=1 fleet produces identical token streams under either
  driver with zero added recompiles, and kill-one-of-two under the
  reactor fails over bit-exactly with the survivor's fused step still
  compiled once;
* the HTTP/SSE stream byte-assembles to exactly what a direct
  ``submit()`` returns — tokens surface per engine iteration via the
  scheduler's ``on_tokens`` push (never by polling ``finished``);
* a client that disconnects mid-stream cancels its request (reason
  ``"cancelled"``, slot and blocks freed, trace flow finalized, no
  stats double-count), and a reader too slow for its bounded queue
  sheds ONLY its own flow;
* under real process faults (SIGKILL / SIGSTOP) behind the reactor,
  zero requests are lost and none double-served.
"""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.models.gpt import generate
from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.serving import Request, Router
from easyparallellibrary_tpu.serving.frontdoor import (
    FrontDoor, generate as fd_generate, healthz, stream_generate)
from easyparallellibrary_tpu.serving.frontdoor.server import _StreamState
from easyparallellibrary_tpu.serving.reactor import RouterReactor
from easyparallellibrary_tpu.serving.scheduler import FinishedRequest
from easyparallellibrary_tpu.testing import chaos

TINY = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                 d_ff=64, max_seq_len=32, dtype=jnp.float32)
FACTORY = {"fn": "easyparallellibrary_tpu.testing.factories:tiny_gpt"}


def _model_and_params(cfg=TINY, seed=0):
  model = GPT(cfg)
  params = model.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, 4), jnp.int32))["params"]
  return model, params


def _prompts(lengths, vocab=64, seed=0):
  r = np.random.RandomState(seed)
  return [r.randint(0, vocab, (n,)).astype(np.int32) for n in lengths]


def _oracle(model, params, prompt, max_new):
  return np.asarray(
      generate(model, params, jnp.asarray(prompt)[None], max_new))[0]


def _config(reactor=True, **frontdoor):
  conf = {"serving": {"router": {"reactor": reactor}}}
  if frontdoor:
    conf["serving"]["frontdoor"] = frontdoor
  return epl.Config(conf)


def _wait_for(predicate, timeout_s=15.0, interval_s=0.02):
  deadline = time.monotonic() + timeout_s
  while time.monotonic() < deadline:
    if predicate():
      return True
    time.sleep(interval_s)
  return predicate()


# ------------------------------------------- reactor: sweep equivalence


@pytest.mark.quick
def test_reactor_inproc_n1_bit_exact_with_sweep_zero_recompile():
  """Tentpole pin 1: the reactor over an in-process N=1 fleet is a pure
  re-cadencing of the SAME engine steps — token streams bit-identical
  to the sweep driver (and the generate() oracle) with the one fused
  step still compiled ONCE under either driver."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3, 9, 2))
  max_new = (6, 7, 4, 5)

  def drive(router, step_once, run):
    for i in range(2):
      assert router.submit(Request(uid=i, prompt=prompts[i],
                                   max_new_tokens=max_new[i]))
    out = {}
    for _ in range(2):
      for fin in step_once():
        out[fin.uid] = fin.tokens
    for i in range(2, 4):                       # staggered second wave
      assert router.submit(Request(uid=i, prompt=prompts[i],
                                   max_new_tokens=max_new[i]))
    out.update(run())
    return out

  sweep = Router(model, params, num_replicas=1, num_slots=2,
                 prefill_chunk=4, config=_config(reactor=False))
  swept = drive(sweep, sweep.step, sweep.run)

  rrouter = Router(model, params, num_replicas=1, num_slots=2,
                   prefill_chunk=4, config=_config(reactor=True))
  reactor = rrouter.reactor()
  assert isinstance(reactor, RouterReactor)
  assert rrouter.reactor() is reactor            # cached, one per router
  reacted = drive(rrouter, reactor.cycle, rrouter.run)

  for router in (sweep, rrouter):
    assert router.replicas[0].engine._step_fn._cache_size() == 1, \
        "the reactor must add ZERO recompiles"
    assert router.failovers == 0 and router.states() == ["healthy"]
  assert reactor.cycles > 0 and reactor.dispatched > 0
  assert sorted(swept) == sorted(reacted) == list(range(4))
  for i in range(4):
    np.testing.assert_array_equal(reacted[i], swept[i],
                                  err_msg=f"req {i}")
    np.testing.assert_array_equal(
        reacted[i], _oracle(model, params, prompts[i], max_new[i]))
    assert rrouter.finished[i].finish_reason == "length"


@pytest.mark.quick
def test_replica_kill_under_reactor_bit_exact_failover():
  """Tentpole pin 2: kill one of two in-process replicas mid-decode
  UNDER THE REACTOR — failover runs the same unmodified router
  machinery, every request finishes with the exact oracle stream, and
  the survivor's fused step stays compiled once."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3, 9, 2), seed=8)
  router = Router(model, params, num_replicas=2, num_slots=2,
                  prefill_chunk=4, config=_config(reactor=True))
  killer = chaos.ReplicaKiller(router.replicas[0].engine,
                               kill_calls=(3,))
  for i, p in enumerate(prompts):
    assert router.submit(Request(uid=i, prompt=p, max_new_tokens=6))
  assert {router.placement[i] for i in range(4)} == {0, 1}
  out = router.run()                       # delegates to the reactor
  assert router.reactor().cycles > 0
  assert killer.kills == 1
  assert router.failovers == 1 and router.migrated_requests == 2
  assert router.states() == ["down", "healthy"]
  assert router.replicas[1].engine._step_fn._cache_size() == 1, \
      "failover under the reactor must not recompile the survivor"
  assert len(router.finished) == 4
  for i, p in enumerate(prompts):
    assert router.finished[i].finish_reason == "length"
    np.testing.assert_array_equal(out[i], _oracle(model, params, p, 6),
                                  err_msg=f"req {i}")
  fleet = router.fleet_summary()
  assert fleet["finished_requests"] == 4.0      # nothing double-counted
  assert fleet["failovers"] == 1.0


# ------------------------------------------------ HTTP/SSE equivalence


@pytest.mark.quick
def test_http_sse_stream_assembles_to_direct_submit():
  """Tentpole pin 3: the HTTP/SSE stream byte-assembles to exactly the
  tokens a direct ``submit()`` produces — per-iteration push events
  (the on_tokens feed), then one ``done`` — over the real socket."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3, 7), seed=3)
  max_new = (8, 6, 5)

  direct = Router(model, params, num_replicas=1, num_slots=2,
                  prefill_chunk=4, config=_config(reactor=False))
  for i, p in enumerate(prompts):
    assert direct.submit(Request(uid=i, prompt=p, max_new_tokens=max_new[i]))
  direct_out = direct.run()

  router = Router(model, params, num_replicas=1, num_slots=2,
                  prefill_chunk=4, config=_config(reactor=True))
  with FrontDoor(router) as fd:
    assert healthz(fd.address)["states"] == ["healthy"]
    for i, p in enumerate(prompts):
      events = list(stream_generate(
          fd.address, {"uid": f"h{i}", "prompt": [int(t) for t in p],
                       "max_new_tokens": max_new[i]}))
      token_events = [d for e, d in events if e == "token"]
      dones = [d for e, d in events if e == "done"]
      assert len(dones) == 1, "exactly one done event per stream"
      assert dones[0]["finish_reason"] == "length"
      assert dones[0]["new_tokens"] == max_new[i]
      assert not dones[0]["truncated"]
      # Per-iteration push: one token event per engine iteration that
      # committed for this request — never one big final batch.
      assert len(token_events) > 1
      streamed = [t for d in token_events for t in d["tokens"]]
      assembled = [int(t) for t in p] + streamed
      np.testing.assert_array_equal(
          assembled, direct_out[i],
          err_msg=f"stream h{i} must byte-assemble to direct submit")
    assert fd.streamed_events >= sum(max_new) - len(max_new)
  assert router.replicas[0].engine._step_fn._cache_size() == 1


def test_header_mapping_and_request_validation():
  """X-Deadline-S / X-TTFT-Budget-S / X-Priority map onto the
  scheduler's Request fields (headers win over body fields), malformed
  requests get 400s, and a shed admission surfaces as a ``done`` with
  reason ``"shed"`` — all over the real socket."""

  class FakeRouter:
    def __init__(self):
      self.on_tokens = []
      self.finished = {}
      self.captured = []
      self.steps = 0
      self.has_work = False

    def submit(self, request):
      self.captured.append(request)
      prompt = np.asarray(request.prompt, np.int32)
      self.finished[request.uid] = FinishedRequest(
          uid=request.uid, tokens=prompt, new_tokens=0,
          finish_reason="shed")
      return False

    def cancel(self, uid):
      return False

    def step(self):
      return []

    def states(self):
      return ["healthy"]

  router = FakeRouter()
  with FrontDoor(router, config=_config(reactor=False)) as fd:
    toks, done = fd_generate(
        fd.address,
        {"prompt": [1, 2, 3], "max_new_tokens": 4, "deadline_s": 9.0,
         "temperature": 0.5, "top_k": 7, "seed": 11},
        headers={"X-Deadline-S": "2.5", "X-TTFT-Budget-S": "0.75",
                 "X-Priority": "latency"})
    assert toks == [] and done["finish_reason"] == "shed"
    (req,) = router.captured
    assert req.deadline_s == 2.5          # header wins over body's 9.0
    assert req.ttft_budget_s == 0.75
    assert req.priority == "latency"
    assert req.max_new_tokens == 4 and req.temperature == 0.5
    assert req.top_k == 7 and req.seed == 11
    np.testing.assert_array_equal(req.prompt, [1, 2, 3])

    for body, hdrs in [
        ({"prompt": [], "max_new_tokens": 4}, None),
        ({"prompt": "not-ids"}, None),
        ({"prompt": [1, 2]}, {"X-Priority": "urgent"}),
        ({"prompt": [1, 2]}, {"X-Deadline-S": "soon"}),
    ]:
      with pytest.raises(RuntimeError, match="HTTP 400"):
        list(stream_generate(fd.address, body, headers=hdrs))


# ------------------------------------- cancel-on-disconnect + shedding


def test_cancel_on_disconnect_frees_slot_and_finalizes_flow():
  """Satellite 3: a client that drops mid-stream cancels its request —
  retired with reason ``"cancelled"``, slot and blocks freed, trace
  flow finalized with the cancel reason, and the fleet counts the
  request exactly once."""
  epl.init()
  tracer = trace_lib.install(
      trace_lib.Tracer(enabled=True, ring_capacity=4096))
  try:
    model, params = _model_and_params()
    (prompt,) = _prompts((6,), seed=5)
    router = Router(model, params, num_replicas=1, num_slots=2,
                    prefill_chunk=4,
                    config=_config(reactor=True, keepalive_s=0.1,
                                   write_timeout_s=2.0))
    engine = router.replicas[0].engine
    # Pace the engine (~25ms/step) so the drop lands MID-stream.
    chaos.HangingStepInjector(engine, hang_calls=range(1, 500),
                              hang_s=0.025)
    with FrontDoor(router) as fd:
      client = chaos.DisconnectingClient(
          fd.address,
          {"uid": "gone", "prompt": [int(t) for t in prompt],
           "max_new_tokens": 24},
          after_events=2, rst=True)
      client.start()
      client.join(timeout=30.0)
      assert client.dropped and client.error is None
      assert 2 <= client.events_seen < 24
      assert _wait_for(
          lambda: router.finished.get("gone") is not None
          and router.finished["gone"].finish_reason == "cancelled"), \
          "disconnect must cancel the request within a keepalive beat"
      fin = router.finished["gone"]
      assert fin.finish_reason == "cancelled"
      assert 0 < fin.new_tokens < 24
      assert _wait_for(lambda: not engine.has_work)
      assert engine.scheduler.active == {}, "slot must be freed"
      assert fd.disconnect_cancels == 1
    assert router.fleet_summary()["finished_requests"] == 1.0, \
        "a cancelled stream must not double-count"
    finishes = [e for e in tracer.events() if e.get("ph") == "f"
                and e.get("args", {}).get("uid") == "gone"]
    assert finishes, "the request's trace flow must be finalized"
    assert finishes[-1]["args"]["reason"] == "cancelled"
  finally:
    trace_lib.reset()


def test_slow_reader_overflow_sheds_only_its_flow():
  """Satellite 2 core invariant: a reader that never drains its bounded
  queue overflows it; the front door cancels THAT uid after the cycle
  (never reentrantly inside commit) while a concurrently streaming
  neighbour finishes bit-exactly."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((6, 5), seed=7)
  oracle = _oracle(model, params, prompts[1], 8)
  router = Router(model, params, num_replicas=1, num_slots=2,
                  prefill_chunk=4,
                  config=_config(reactor=True, stream_buffer=2))
  with FrontDoor(router) as fd:
    # An infinitely slow reader, as the server sees one: its stream
    # state exists but nothing ever drains the queue.
    stuck = _StreamState("stuck", prompt_len=len(prompts[0]), buffer=2)
    with fd._streams_lock:
      fd._streams["stuck"] = stuck
    fd._commands.put(("submit", Request(
        uid="stuck", prompt=prompts[0], max_new_tokens=16), stuck))
    assert stuck.admitted.wait(timeout=30.0) and stuck.accepted

    toks, done = fd_generate(
        fd.address, {"uid": "ok", "prompt": [int(t) for t in prompts[1]],
                     "max_new_tokens": 8})
    assert done["finish_reason"] == "length"
    np.testing.assert_array_equal(
        [int(t) for t in prompts[1]] + toks, oracle,
        err_msg="the neighbour of a shed flow must stream bit-exactly")

    assert _wait_for(
        lambda: router.finished.get("stuck") is not None
        and router.finished["stuck"].finish_reason == "cancelled"), \
        "queue overflow must shed the slow flow"
    assert stuck.overflow
    assert fd.overflow_sheds == 1
    assert stuck.final is not None
    assert stuck.final["finish_reason"] == "cancelled"
    # The bound held: never more batches buffered than configured.
    assert stuck.queue.qsize() <= 2
  assert router.fleet_summary()["finished_requests"] == 2.0


# ------------------------------------ chaos suite (make chaos-frontdoor)


def _serve_clients(fd, prompts, max_new, start=0):
  """Drive one HTTP generate() per prompt from its own thread; returns
  uid -> (streamed_tokens, done) plus any per-thread error."""
  results, errors = {}, {}

  def one(i, p):
    uid = f"c{start + i}"
    try:
      results[uid] = fd_generate(
          fd.address, {"uid": uid, "prompt": [int(t) for t in p],
                       "max_new_tokens": max_new}, timeout=120.0)
    except Exception as e:      # noqa: BLE001 — recorded for the assert
      errors[uid] = e

  threads = [threading.Thread(target=one, args=(i, p), daemon=True)
             for i, p in enumerate(prompts)]
  for t in threads:
    t.start()
  for t in threads:
    t.join(timeout=120.0)
  return results, errors


def _process_config(**over):
  conf = {"serving": {"router": {
      "transport": "process", "reactor": True, "rpc_timeout_s": 60.0,
      "rpc_retries": 2, "rpc_backoff_s": 0.05}}}
  conf["serving"]["router"].update(over)
  return epl.Config(conf)


@pytest.mark.slow
def test_chaos_frontdoor_sigkill_under_reactor_zero_lost():
  """`make chaos-frontdoor` headline: SIGKILL one of two process
  replicas mid-episode behind the reactor-driven front door — every
  connected client still byte-assembles its exact oracle stream (zero
  lost), each stream resolves exactly once (zero double-served), and a
  disconnecting client's request is cancelled, not resurrected."""
  from easyparallellibrary_tpu.testing.factories import tiny_gpt
  model, params = tiny_gpt()
  prompts = _prompts((6, 6, 6, 6), seed=11)
  oracle = {f"c{i}": _oracle(model, params, p, 10)
            for i, p in enumerate(prompts)}
  router = Router(num_replicas=2, config=_process_config(),
                  factory=FACTORY, num_slots=4, prefill_chunk=4)
  victim = router.replicas[0]
  with FrontDoor(router) as fd:
    killer_fired = threading.Event()

    def kill_soon():
      _wait_for(lambda: victim.has_work, timeout_s=60.0)
      chaos.ProcessKiller(victim).kill()
      killer_fired.set()

    threading.Thread(target=kill_soon, daemon=True).start()
    results, errors = _serve_clients(fd, prompts, max_new=10)
    assert killer_fired.wait(timeout=60.0)
    assert not errors, f"no client may error through the kill: {errors}"
    assert set(results) == set(oracle), "zero lost requests"
    for uid, (toks, done) in results.items():
      assert done["finish_reason"] == "length", uid
      prompt = [int(t) for t in prompts[int(uid[1:])]]
      np.testing.assert_array_equal(prompt + toks, oracle[uid],
                                    err_msg=uid)
    assert router.failovers >= 1
  # Exactly-once fleet-wide: one resolution per uid, none double-served.
  assert sorted(router.finished) == sorted(oracle)
  assert router.fleet_summary()["finished_requests"] == float(len(oracle))
  router.close()


@pytest.mark.slow
def test_chaos_frontdoor_sigstop_hang_under_reactor_heals():
  """SIGSTOP (a genuinely frozen child — the straggler case the
  reactor's wire deadline must surface): the condemned replica is
  fenced and failed over, every client still completes bit-exactly,
  and a SlowReader trickling its own stream harms no neighbour."""
  from easyparallellibrary_tpu.testing.factories import tiny_gpt
  model, params = tiny_gpt()
  prompts = _prompts((6, 6, 6), seed=13)
  oracle = {f"c{i}": _oracle(model, params, p, 8)
            for i, p in enumerate(prompts)}
  router = Router(num_replicas=2,
                  config=_process_config(rpc_timeout_s=3.0),
                  factory=FACTORY, num_slots=4, prefill_chunk=4)
  victim = router.replicas[0]
  with FrontDoor(router) as fd:
    (slow_prompt,) = _prompts((5,), seed=14)
    slow = chaos.SlowReader(
        fd.address, {"uid": "slow", "prompt": [int(t) for t in slow_prompt],
                     "max_new_tokens": 4},
        read_bytes=16, interval_s=0.05, duration_s=60.0)
    slow.start()

    def stall_soon():
      _wait_for(lambda: victim.has_work, timeout_s=60.0)
      staller = chaos.ProcessStaller(victim)
      staller.stall()

    threading.Thread(target=stall_soon, daemon=True).start()
    results, errors = _serve_clients(fd, prompts, max_new=8)
    assert not errors, f"no client may error through the stall: {errors}"
    for uid, (toks, done) in results.items():
      prompt = [int(t) for t in prompts[int(uid[1:])]]
      np.testing.assert_array_equal(prompt + toks, oracle[uid],
                                    err_msg=uid)
    # The slow reader's own flow resolves too — served or shed, never
    # lost, never harming the neighbours asserted above.
    assert _wait_for(lambda: "slow" in router.finished, timeout_s=60.0)
    assert router.finished["slow"].finish_reason in ("length",
                                                     "cancelled")
    assert _wait_for(lambda: router.failovers >= 1, timeout_s=60.0), \
        "the frozen child must be condemned and failed over"
  router.close()


# ------------------------------------ trace-context propagation (W3C)


def test_traceparent_parse_and_mint_units():
  """Strict W3C parsing: a valid header decomposes, a minted header
  round-trips, and every malformed shape is a ValueError (the 400
  path) — never a silently broken trace."""
  from easyparallellibrary_tpu.serving.frontdoor.server import (
      flow_id_from_trace_id, mint_traceparent, parse_traceparent)
  tid, pid, flags = parse_traceparent(
      "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01")
  assert tid == "4bf92f3577b34da6a3ce929d0e0e4736"
  assert pid == "00f067aa0ba902b7" and flags == "01"
  # flow_id keeps the trace-id's low 53 bits (exact as a JSON number).
  assert flow_id_from_trace_id(tid) == int(tid, 16) & ((1 << 53) - 1)
  minted = mint_traceparent(12345)
  tid2, _, _ = parse_traceparent(minted)
  assert flow_id_from_trace_id(tid2) == 12345
  for bad in [
      "",                                                  # empty
      "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7",  # 3 parts
      "zz-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01",
      "ff-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01",
      "00-4bf92f3577b34da6-00f067aa0ba902b7-01",           # short tid
      "00-4BF92F3577B34DA6A3CE929D0E0E4736-00f067aa0ba902b7-01",
      "00-" + "0" * 32 + "-00f067aa0ba902b7-01",           # zero tid
      "00-4bf92f3577b34da6a3ce929d0e0e4736-" + "0" * 16 + "-01",
      "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-0g",
  ]:
    with pytest.raises(ValueError):
      parse_traceparent(bad)


def test_traceparent_propagation_echo_and_400_over_socket():
  """Over the real socket: a caller's ``traceparent`` maps onto the
  submitted Request's flow_id and is echoed back verbatim beside
  ``X-Request-Id``; an absent header gets a minted one carrying the
  flow id; a malformed header is a 400, not a broken trace."""
  from easyparallellibrary_tpu.serving.frontdoor.client import _post
  from easyparallellibrary_tpu.serving.frontdoor.server import (
      flow_id_from_trace_id, parse_traceparent)

  class FakeRouter:
    def __init__(self):
      self.on_tokens = []
      self.finished = {}
      self.captured = []
      self.has_work = False

    def submit(self, request):
      self.captured.append(request)
      self.finished[request.uid] = FinishedRequest(
          uid=request.uid, tokens=np.asarray(request.prompt, np.int32),
          new_tokens=0, finish_reason="shed")
      return False

    def cancel(self, uid):
      return False

    def step(self):
      return []

    def states(self):
      return ["healthy"]

  header = "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01"
  want_flow = flow_id_from_trace_id("4bf92f3577b34da6a3ce929d0e0e4736")
  router = FakeRouter()
  with FrontDoor(router, config=_config(reactor=False)) as fd:
    resp = _post(fd.address, {"uid": "tp-1", "prompt": [1, 2, 3],
                              "max_new_tokens": 2},
                 {"traceparent": header}, timeout=30.0)
    assert resp.status == 200
    assert resp.getheader("X-Request-Id") == "tp-1"
    assert resp.getheader("traceparent") == header
    resp.read()
    resp.close()
    (req,) = router.captured
    assert req.flow_id == want_flow

    # Absent header: the front door mints one carrying the flow id it
    # assigned, so the caller can still join its logs to the trace.
    resp = _post(fd.address, {"uid": "tp-2", "prompt": [4, 5],
                              "max_new_tokens": 2}, None, timeout=30.0)
    assert resp.status == 200
    minted = resp.getheader("traceparent")
    resp.read()
    resp.close()
    tid, _, _ = parse_traceparent(minted)
    assert flow_id_from_trace_id(tid) == router.captured[-1].flow_id
    assert router.captured[-1].flow_id  # really minted, non-zero

    for bad in ["garbage", "00-dead-beef-01",
                "ff-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01"]:
      with pytest.raises(RuntimeError, match="HTTP 400"):
        list(stream_generate(fd.address, {"prompt": [1]},
                             headers={"traceparent": bad}))
    assert len(router.captured) == 2, "malformed headers never submit"
