"""End-to-end data-parallel training, numerically equivalent to
single-device (reference analog: tests/dnn_data_parallel.py + the fixed-seed
loss-comparison style of tests/zero_test.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import optax
from flax import linen as nn

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.parallel import (
    TrainState, create_sharded_train_state, make_train_step, parallelize,
)


class MLP(nn.Module):
  features: int = 32

  @nn.compact
  def __call__(self, x):
    x = nn.Dense(self.features)(x)
    x = nn.relu(x)
    x = nn.Dense(self.features)(x)
    x = nn.relu(x)
    return nn.Dense(1)(x)


def _make_data(n=64, d=16, seed=0):
  rng = np.random.RandomState(seed)
  x = rng.randn(n, d).astype(np.float32)
  w = rng.randn(d, 1).astype(np.float32)
  y = x @ w + 0.1 * rng.randn(n, 1).astype(np.float32)
  return jnp.asarray(x), jnp.asarray(y)


def _loss_fn(apply_fn):
  def loss(params, batch, rng):
    pred = apply_fn({"params": params}, batch["x"])
    return jnp.mean((pred - batch["y"]) ** 2), {}
  return loss


def _train(n_steps=5):
  """One DP training run under the framework; returns losses + params."""
  env = epl.init()
  with epl.replicate(1):
    model = MLP()
  plan = epl.current_plan()
  mesh = plan.build_mesh()

  x, y = _make_data()
  tx = optax.sgd(0.05)

  def init_fn(rng):
    params = model.init(rng, x[:1])["params"]
    return TrainState.create(apply_fn=model.apply, params=params, tx=tx)

  rng = jax.random.PRNGKey(42)
  state, shardings = create_sharded_train_state(init_fn, mesh, rng)
  step = parallelize(make_train_step(_loss_fn(model.apply)),
                     mesh, shardings)

  losses = []
  for i in range(n_steps):
    state, metrics = step(state, {"x": x, "y": y}, rng)
    losses.append(float(metrics["loss"]))
  return losses, jax.device_get(state.params)


def _train_baseline(n_steps=5):
  """Plain single-device jax training loop with identical seeds."""
  model = MLP()
  x, y = _make_data()
  tx = optax.sgd(0.05)
  rng = jax.random.PRNGKey(42)
  params = model.init(rng, x[:1])["params"]
  opt_state = tx.init(params)

  def loss(params, batch):
    pred = model.apply({"params": params}, batch["x"])
    return jnp.mean((pred - batch["y"]) ** 2)

  @jax.jit
  def step(params, opt_state, batch):
    l, grads = jax.value_and_grad(loss)(params, batch)
    updates, opt_state = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, l

  losses = []
  for i in range(n_steps):
    params, opt_state, l = step(params, opt_state, {"x": x, "y": y})
    losses.append(float(l))
  return losses, jax.device_get(params)


@pytest.mark.quick
def test_dp_matches_single_device():
  dp_losses, dp_params = _train()
  base_losses, base_params = _train_baseline()
  np.testing.assert_allclose(dp_losses, base_losses, rtol=1e-5, atol=1e-6)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
      dp_params, base_params)


def test_dp_loss_decreases():
  losses, _ = _train(n_steps=10)
  assert losses[-1] < losses[0]


def test_batch_is_sharded_on_data_axis():
  env = epl.init()
  with epl.replicate(1):
    model = MLP()
  mesh = epl.current_plan().build_mesh()
  from easyparallellibrary_tpu.parallel import batch_sharding
  x = jax.device_put(jnp.zeros((16, 4)), batch_sharding(mesh))
  # Each device should hold 1/8 of the batch.
  assert x.sharding.shard_shape(x.shape) == (2, 4)
