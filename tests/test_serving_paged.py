"""Paged KV cache + token-flat fused step (ISSUE 7).

The exactness contract under test: the paged engine is a pure
REBATCHING of the same math — greedy token ids are bit-identical per
request to BOTH ``generate(use_cache=True)`` and the contiguous slot
engine (itself quick-pinned to generate), no matter when a request was
admitted, which blocks its K/V landed in, who owned those blocks
before, or whether the block pool ran dry and preempted it mid-flight.
Compile count stays 1 as requests join/leave and block tables reshuffle.
Heavyweight shape sweeps are ``slow``-marked so tier-1 keeps its window;
the Pallas kernel parity test is TPU-gated (skip-not-fail on CPU — the
CPU engine runs the bit-exact jnp reference path, which these tests
exercise throughout).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.kernels.paged_attention import (
    paged_attention_pallas, paged_attention_reference)
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.models.gpt import generate
from easyparallellibrary_tpu.serving import (
    BlockAllocator, ContinuousBatchingEngine, DraftModelDrafter, Request,
    allocate_paged_kv_cache, blocks_per_slot, default_num_blocks,
    paged_cache_bytes)
from easyparallellibrary_tpu.testing import chaos

TINY = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                 d_ff=64, max_seq_len=32, dtype=jnp.float32)


def _model_and_params(cfg=TINY, seed=0):
  model = GPT(cfg)
  params = model.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, 4), jnp.int32))["params"]
  return model, params


def _prompts(lengths, vocab=64, seed=0):
  r = np.random.RandomState(seed)
  return [r.randint(0, vocab, (n,)).astype(np.int32) for n in lengths]


def _oracle(model, params, prompt, max_new):
  return np.asarray(
      generate(model, params, jnp.asarray(prompt)[None], max_new))[0]


# --------------------------------------------------------------- exactness


@pytest.mark.quick
def test_paged_greedy_exact_staggered_compile_once():
  """Token-flat paged decode is bit-exact vs generate(use_cache=True)
  per request — admissions staggered mid-flight, slots AND blocks reused
  across retirements — with fused-step compile count == 1 throughout
  (joins, leaves and block-table reshuffles are data)."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3, 9, 1, 6, 2))
  max_new = (6, 7, 8, 4, 5, 9)
  eng = ContinuousBatchingEngine(model, params, num_slots=3,
                                 prefill_chunk=4, paged=True,
                                 block_size=4)
  for i in range(3):
    eng.submit(Request(uid=i, prompt=prompts[i],
                       max_new_tokens=max_new[i]))
  out = {}
  for _ in range(2):  # second wave joins a mid-flight batch
    for fin in eng.step():
      out[fin.uid] = fin.tokens
  for i in range(3, len(prompts)):
    eng.submit(Request(uid=i, prompt=prompts[i],
                       max_new_tokens=max_new[i]))
  out.update(eng.run())
  assert eng._step_fn._cache_size() == 1
  for i, p in enumerate(prompts):
    np.testing.assert_array_equal(
        out[i], _oracle(model, params, p, max_new[i]), err_msg=f"req {i}")
  # Retirement returned every block (no leaks, no dangling refcounts).
  assert eng.scheduler.kv_blocks_used == 0


@pytest.mark.quick
def test_paged_tp2_staggered_exact_vs_nonpaged_engine():
  """The paged engine on a TP=2 virtual mesh (heads sharded over
  `model`, pools allocated sharded) reproduces the NON-paged engine's
  greedy ids exactly under staggered admission — the contiguous engine
  is itself quick-pinned to generate, so the chain pins paged → slot →
  oracle."""
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state)
  import optax
  epl.init(epl.Config({"cluster.mesh_shape": "data:4,model:2"}))
  mesh = epl.Env.get().cluster.build_mesh()
  cfg = GPTConfig(**{**TINY.__dict__, "tensor_parallel": True})
  model = GPT(cfg)
  prompts = _prompts((4, 7, 2, 5), seed=1)

  def init_fn(rng):
    return TrainState.create(
        apply_fn=model.apply,
        params=model.init(rng, jnp.asarray(prompts[0])[None])["params"],
        tx=optax.sgd(0.1))

  state, _ = create_sharded_train_state(init_fn, mesh,
                                        jax.random.PRNGKey(5))

  def drive(paged: bool, drafter=None):
    eng = ContinuousBatchingEngine(model, state.params, mesh=mesh,
                                   num_slots=2, prefill_chunk=4,
                                   paged=paged, block_size=4,
                                   drafter=drafter)
    for i, p in enumerate(prompts[:2]):
      eng.submit(Request(uid=i, prompt=p, max_new_tokens=5))
    out = {}
    for fin in eng.step():       # later submits join mid-flight
      out[fin.uid] = fin.tokens
    for i in range(2, len(prompts)):
      eng.submit(Request(uid=i, prompt=prompts[i], max_new_tokens=5))
    out.update(eng.run())
    assert eng._step_fn._cache_size() == 1
    return out

  from easyparallellibrary_tpu.serving import NgramDrafter
  paged_out, slot_out = drive(True), drive(False)
  # The speculative twin has its own mesh sharding signature (more
  # replicated inputs) — pin the meshed paged+spec combination too.
  spec_out = drive(True, drafter=NgramDrafter(k=2))
  for i in range(len(prompts)):
    np.testing.assert_array_equal(paged_out[i], slot_out[i],
                                  err_msg=f"req {i}")
    np.testing.assert_array_equal(spec_out[i], slot_out[i],
                                  err_msg=f"spec req {i}")


@pytest.mark.quick
def test_block_reuse_after_retirement_no_stale_kv():
  """A retired request's freed blocks are re-issued (lowest-free-first)
  to the next occupant with no stale-KV leakage: a SHORT request served
  after a LONG one reuses the same physical blocks yet matches its
  from-scratch oracle bit-exactly."""
  epl.init()
  model, params = _model_and_params(seed=2)
  long_p, short_p = _prompts((12, 3), seed=3)
  eng = ContinuousBatchingEngine(model, params, num_slots=1,
                                 prefill_chunk=4, paged=True,
                                 block_size=4)
  eng.submit(Request(uid="long", prompt=long_p, max_new_tokens=10))
  eng.step()
  long_blocks = set(eng.scheduler.slot_blocks(0))
  out = eng.run()
  eng.submit(Request(uid="short", prompt=short_p, max_new_tokens=6))
  eng.step()
  short_blocks = set(eng.scheduler.slot_blocks(0))
  out.update(eng.run())
  # The short request's blocks physically overlap the long one's —
  # the no-leakage property is doing real work here.
  assert short_blocks and short_blocks <= long_blocks
  np.testing.assert_array_equal(out["long"],
                                _oracle(model, params, long_p, 10))
  np.testing.assert_array_equal(out["short"],
                                _oracle(model, params, short_p, 6))


@pytest.mark.quick
def test_block_pool_exhaustion_preempts_and_replays_exact():
  """Pool exhaustion pages out the youngest lowest-priority slot via the
  requeue prefix-replay path (reason "preempted") instead of raising;
  both the survivor and the preempted request finish bit-exact, the one
  compiled step is reused, and every block returns to the pool."""
  epl.init()
  model, params = _model_and_params()
  p1, p2 = _prompts((10, 10), seed=7)
  # 9 usable blocks x 4 = 36 rows < 2 requests x 24 rows: must preempt.
  eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                 prefill_chunk=4, paged=True,
                                 block_size=4, num_blocks=10)
  eng.submit(Request(uid="a", prompt=p1, max_new_tokens=14))
  eng.submit(Request(uid="b", prompt=p2, max_new_tokens=14))
  out = eng.run(max_steps=300)
  assert eng.scheduler.preemptions >= 1
  assert eng._step_fn._cache_size() == 1
  for uid, p in (("a", p1), ("b", p2)):
    assert eng.finished[uid].finish_reason == "length"
    np.testing.assert_array_equal(out[uid], _oracle(model, params, p, 14),
                                  err_msg=uid)
  assert eng.scheduler.kv_blocks_used == 0
  assert eng.scheduler.kv_blocks_free == 9


@pytest.mark.slow
def test_paged_speculative_bit_exact_both_drafters():
  """Greedy speculative paged decode keeps the oracle bitstream: drafts
  ride leftover flat-budget positions, verification gathers target rows
  by flat index, and rejection is pure host bookkeeping (no cursors to
  roll back).  Same-params draft model guarantees multi-token accepted
  bursts; the n-gram drafter exercises partial/empty proposals."""
  from easyparallellibrary_tpu.serving import NgramDrafter
  epl.init()
  model, params = _model_and_params(seed=4)
  prompts = _prompts((5, 3, 9), seed=5)
  max_new = (8, 7, 10)
  for drafter in (DraftModelDrafter(model, params, k=3),
                  NgramDrafter(k=3)):
    eng = ContinuousBatchingEngine(model, params, num_slots=3,
                                   prefill_chunk=4, paged=True,
                                   block_size=4, drafter=drafter)
    for i, p in enumerate(prompts):
      eng.submit(Request(uid=i, prompt=p, max_new_tokens=max_new[i]))
    out = eng.run()
    assert eng._step_fn._cache_size() == 1
    assert eng._drafter_failures == 0
    for i, p in enumerate(prompts):
      np.testing.assert_array_equal(
          out[i], _oracle(model, params, p, max_new[i]),
          err_msg=f"{type(drafter).__name__} req {i}")


def test_paged_draft_model_longer_max_seq_len_binds_and_stays_exact():
  """A draft model padded LONGER than the target (which
  check_draft_compatible explicitly permits) must bind: the mirror pool
  is addressed through the ENGINE's block tables, so its capacity check
  uses the target's geometry, not the draft's wider one — and greedy
  stays bit-exact regardless of drafter shape."""
  epl.init()
  model, params = _model_and_params(seed=9)
  draft_cfg = GPTConfig(**{**TINY.__dict__, "max_seq_len": 64,
                           "num_layers": 1})
  draft_model = GPT(draft_cfg)
  draft_params = draft_model.init(jax.random.PRNGKey(1),
                                  jnp.zeros((1, 4), jnp.int32))["params"]
  (p,) = _prompts((6,), seed=10)
  eng = ContinuousBatchingEngine(
      model, params, num_slots=2, prefill_chunk=4, paged=True,
      block_size=4,
      drafter=DraftModelDrafter(draft_model, draft_params, k=2))
  eng.submit(Request(uid="x", prompt=p, max_new_tokens=6))
  out = eng.run()
  assert eng._drafter_failures == 0
  np.testing.assert_array_equal(out["x"], _oracle(model, params, p, 6))


def test_paged_guarded_fault_free_equivalence_and_gauges():
  """Resilience on, no faults: the paged guarded step is bit-identical
  to the unguarded baseline with zero extra compiles, and the block-pool
  gauges flow through ServingStats."""
  epl.init()
  model, params = _model_and_params(seed=6)
  prompts = _prompts((6, 2), seed=8)

  def drive(resilience):
    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   prefill_chunk=4, paged=True,
                                   block_size=4, resilience=resilience)
    for i, p in enumerate(prompts):
      eng.submit(Request(uid=i, prompt=p, max_new_tokens=7))
    out = eng.run()
    assert eng._step_fn._cache_size() == 1
    return eng, out

  eng_r, out_r = drive(True)
  _, out_b = drive(False)
  for i in range(len(prompts)):
    np.testing.assert_array_equal(out_r[i], out_b[i])
  s = eng_r.stats.summary()
  assert s["kv_blocks_free"] > 0 and s["preemptions"] == 0.0
  assert 0.0 <= s["kv_fragmentation"] <= 1.0


def test_paged_nan_step_retried_in_place_bit_exact():
  """A transient NaN device step on the paged engine: the verdict gates
  the commit, the retry re-feeds identical flat work (positions are
  host-planned — no cursor fetch), the poisoned rows (and the null
  block) are zeroed, and the final stream is bit-identical."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3))
  eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                 prefill_chunk=4, paged=True,
                                 block_size=4, resilience=True)
  inj = chaos.NaNLogitsInjector(eng, bad_calls=(2,))
  for i, p in enumerate(prompts):
    eng.submit(Request(uid=i, prompt=p, max_new_tokens=6))
  out = eng.run()
  assert inj.poisoned == [2]
  assert inj._cache_size() == 1
  assert eng.stats.bad_steps == 1 and eng.stats.step_retries >= 1
  for i, p in enumerate(prompts):
    assert eng.finished[i].finish_reason == "length"
    np.testing.assert_array_equal(out[i], _oracle(model, params, p, 6),
                                  err_msg=f"req {i}")


# ------------------------------------------------------------------- units


def test_block_allocator_freelist_and_refcounts():
  alloc = BlockAllocator(num_blocks=5, block_size=4)
  assert alloc.num_free == 4          # block 0 reserved (null block)
  a, b = alloc.alloc(), alloc.alloc()
  assert (a, b) == (1, 2)             # lowest-free-first, deterministic
  alloc.incref(a)
  alloc.decref(a)
  assert alloc.refcount(a) == 1       # still held: refcount, not free
  alloc.decref(a)
  assert alloc.refcount(a) == 0 and alloc.num_free == 3
  assert alloc.alloc() == 1           # freed block re-issued lowest-first
  with pytest.raises(ValueError, match="double free|not allocated"):
    alloc.decref(4)
  alloc.decref(b)
  # Fragmentation: 2 allocated blocks (8 rows), 5 resident tokens.
  alloc2 = BlockAllocator(num_blocks=5, block_size=4)
  alloc2.alloc(), alloc2.alloc()
  assert alloc2.fragmentation(5) == pytest.approx(1 - 5 / 8)


def test_paged_geometry_validation():
  model, params = _model_and_params()
  # block_size must divide max_seq_len (reduction-length parity with the
  # oracle — the greedy bit-exactness precondition).
  with pytest.raises(ValueError, match="divide max_seq_len"):
    blocks_per_slot(TINY, 5)
  assert blocks_per_slot(TINY, 4) == 8
  assert default_num_blocks(TINY, 3, 4) == 25
  assert paged_cache_bytes(TINY, 25, 4) == 2 * 2 * 25 * 4 * 32 * 4
  with pytest.raises(ValueError, match="one full-length request"):
    allocate_paged_kv_cache(TINY, 4, 8)
  epl.init()
  # token_budget below the effective batch cap could starve decodes.
  with pytest.raises(ValueError, match="token_budget"):
    ContinuousBatchingEngine(model, params, num_slots=4, prefill_chunk=4,
                             paged=True, block_size=4, token_budget=3)


def test_paged_timeline_blocks_in_report():
  """The per-request timeline shows block occupancy: per-step spans
  carry kv_blocks and report.py rolls up each request's peak."""
  from easyparallellibrary_tpu.observability import trace as trace_lib
  from easyparallellibrary_tpu.observability.report import (
      format_report, request_timelines)
  epl.init()
  tracer = trace_lib.Tracer(enabled=True, ring_capacity=8192)
  trace_lib.install(tracer)
  try:
    model, params = _model_and_params()
    (p,) = _prompts((9,))
    eng = ContinuousBatchingEngine(model, params, num_slots=1,
                                   prefill_chunk=4, paged=True,
                                   block_size=4)
    eng.submit(Request(uid="r", prompt=p, max_new_tokens=6))
    eng.run()
    events = tracer.events()
    rows = request_timelines(events)
    (row,) = [r for r in rows if r["uid"] == "r"]
    # 9 prompt + 6 new tokens => ceil(14/4) = 4 peak blocks.
    assert row["kv_blocks_peak"] == 4
    report = format_report(events)
    assert "blk" in report
  finally:
    trace_lib.install(None)


# ------------------------------------------------------- kernel parity


def _parity_case(seed=0, T=6, H=4, hd=16, NB=9, bs=8, MB=4,
                 dtype=jnp.float32):
  r = np.random.RandomState(seed)
  q = jnp.asarray(r.randn(T, H, hd), dtype)
  kp = jnp.asarray(r.randn(NB, bs, H, hd), dtype)
  vp = jnp.asarray(r.randn(NB, bs, H, hd), dtype)
  tables = jnp.asarray(r.randint(0, NB, (T, MB)), jnp.int32)
  positions = jnp.asarray(r.randint(0, MB * bs, (T,)), jnp.int32)
  return q, kp, vp, tables, positions


def test_paged_kernel_parity_interpret_mode():
  """The Pallas kernel in interpreter mode matches the jnp reference on
  CPU — the kernel's logic is exercised everywhere, not only on TPU."""
  args = _parity_case()
  ref = paged_attention_reference(*args)
  ker = paged_attention_pallas(*args, interpret=True)
  np.testing.assert_allclose(np.asarray(ker), np.asarray(ref),
                             rtol=2e-5, atol=2e-6)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="Pallas paged-attention kernel needs a TPU "
                           "(CPU runs the bit-exact jnp reference path)")
def test_paged_kernel_parity_tpu():
  """On real hardware the compiled kernel matches the reference within
  flash-kernel tolerance (rides the benchmarks/flash_vs_xla.py harness
  pattern: same tolerances, bf16 and fp32 both)."""
  for dtype, rtol, atol in ((jnp.float32, 2e-5, 2e-6),
                            (jnp.bfloat16, 2e-2, 2e-2)):
    args = _parity_case(seed=1, T=16, H=8, hd=64, NB=17, bs=16, MB=8,
                        dtype=dtype)
    ref = paged_attention_reference(*args)
    ker = paged_attention_pallas(*args, interpret=False)
    np.testing.assert_allclose(
        np.asarray(ker, np.float32), np.asarray(ref, np.float32),
        rtol=rtol, atol=atol)


# ------------------------------------------------------------- slow sweeps


@pytest.mark.slow
@pytest.mark.parametrize("block_size,chunk,token_budget",
                         [(2, 3, 7), (8, 4, 16), (16, 5, 9),
                          (32, 4, 23), (4, 1, 5)])
def test_paged_shape_sweep_exact(block_size, chunk, token_budget):
  """Heavyweight sweep: odd chunk widths, one-row blocks-per-slot,
  single-token budgets — every geometry keeps the oracle bitstream."""
  epl.init()
  model, params = _model_and_params(seed=block_size)
  prompts = _prompts((7, 2, 11, 4), seed=chunk)
  max_new = (5, 9, 6, 8)
  eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                 prefill_chunk=chunk, paged=True,
                                 block_size=block_size,
                                 token_budget=token_budget)
  for i, p in enumerate(prompts):
    eng.submit(Request(uid=i, prompt=p, max_new_tokens=max_new[i]))
  out = eng.run(max_steps=500)
  assert eng._step_fn._cache_size() == 1
  for i, p in enumerate(prompts):
    np.testing.assert_array_equal(
        out[i], _oracle(model, params, p, max_new[i]), err_msg=f"req {i}")


@pytest.mark.slow
def test_paged_persistent_nan_quarantine_replays_prefix_exact():
  """Two consecutive poisoned steps quarantine the slot: the request
  requeues with its committed prefix, its freed blocks are zeroed before
  reuse, and the chunked-prefill replay reproduces the oracle stream."""
  epl.init()
  model, params = _model_and_params()
  (p,) = _prompts((5,))
  eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                 prefill_chunk=4, paged=True,
                                 block_size=4, resilience=True)
  inj = chaos.NaNLogitsInjector(eng, bad_calls=(2, 3))
  eng.submit(Request(uid="q", prompt=p, max_new_tokens=6))
  out = eng.run()
  assert inj.poisoned == [2, 3]
  assert inj._cache_size() == 1
  assert eng.stats.requeues == 1
  assert eng.finished["q"].finish_reason == "length"
  np.testing.assert_array_equal(out["q"], _oracle(model, params, p, 6))
