"""Cross-process distributed tracing (ISSUE 20): child trace harvest,
clock-aligned fleet timelines, end-to-end latency decomposition.

The acceptance contract (`make trace-fleet`): with two ProcessTransport
replicas — each recording into its OWN tracer ring — SIGKILL of one
mid-decode still yields ONE merged schema-valid Perfetto trace in which
the failed-over request is a single connected flow spanning the parent
and BOTH child pids, with per-pid monotonic rebased timestamps.  The
fault-free guard: harvest fully enabled changes nothing — streams stay
bit-exact vs the oracle, every fused step compiled once — and a CLEANLY
drained replica's spans ALL appear in the merged trace (the satellite
bugfix: child replicas used to exit without exporting a single span).

The units pin the harvest substrate (drain_wire byte bounds and
delivered-vs-dropped accounting, ingest_remote rebase + per-pid
monotonic clamp + malformed-event tolerance, per-pid export metadata),
the validator's new multi-process negatives, and report.py's hop
decomposition columns.
"""

import json
import os
import signal
import time

import numpy as np
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.observability import report
from easyparallellibrary_tpu.observability import slo as slo_lib
from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.observability.trace import (
    Tracer, validate_trace)
from easyparallellibrary_tpu.serving import (
    ContinuousBatchingEngine, Request, Router)
from easyparallellibrary_tpu.testing import chaos
from easyparallellibrary_tpu.testing.factories import tiny_gpt

FACTORY = {"fn": "easyparallellibrary_tpu.testing.factories:tiny_gpt"}


@pytest.fixture(autouse=True)
def _drop_ambient_observability():
  yield
  trace_lib.reset()
  slo_lib.reset()


def _prompts(n, plen=6, vocab=64, seed=0):
  r = np.random.RandomState(seed)
  return [r.randint(0, vocab, (plen,)).astype(np.int32)
          for _ in range(n)]


def _oracle_outputs(prompts, max_new=10):
  model, params = tiny_gpt()
  eng = ContinuousBatchingEngine(model, params, num_slots=4,
                                 prefill_chunk=4)
  for i, p in enumerate(prompts):
    eng.submit(Request(uid=i, prompt=p, max_new_tokens=max_new))
  out = eng.run()
  eng.close()
  return out


def _dist_config(**router):
  conf = {"transport": "process", "rpc_timeout_s": 60.0,
          "rpc_retries": 2, "rpc_backoff_s": 0.05}
  conf.update(router)
  return epl.Config({"serving": {"router": conf},
                     "observability": {"enabled": True}})


def _assert_no_orphans(pids):
  time.sleep(0.1)
  for pid in pids:
    if pid is None:
      continue
    try:
      os.kill(pid, 0)
    except ProcessLookupError:
      continue
    pytest.fail(f"orphan replica child still alive: pid {pid}")


def _flows(events):
  out = {}
  for ev in events:
    if ev.get("ph") in ("s", "t", "f"):
      out.setdefault(ev["id"], []).append(ev)
  return out


# ------------------------------------------------- harvest substrate


def test_drain_wire_bounded_sweeps_and_accounting():
  """drain_wire consumes OLDEST-first within a byte budget; drained
  events count as delivered (not dropped), the remainder rides later
  sweeps, and ``None`` empties the ring."""
  t = Tracer(ring_capacity=1024)
  for i in range(50):
    t.instant(f"ev{i}", cat="x", args={"i": i})
  assert t.pending == 50 and t.dropped == 0
  chunk = t.drain_wire(256)
  assert chunk["events"], "a sweep within budget must make progress"
  assert len(chunk["events"]) < 50, "256 bytes cannot hold 50 events"
  names = [w[1] for w in chunk["events"]]
  assert names[0] == "ev0", "oldest events leave first"
  assert sum(len(json.dumps(w, separators=(",", ":"), default=str))
             for w in chunk["events"]) <= 256
  assert t.dropped == 0, "drained events were delivered, not dropped"
  rest = t.drain_wire(None)
  assert [w[1] for w in rest["events"]][-1] == "ev49"
  assert t.pending == 0
  assert len(chunk["events"]) + len(rest["events"]) == 50


def test_drain_wire_first_event_always_fits():
  """An event larger than the sweep budget still drains (one per
  sweep) — a single oversized args blob must not wedge the harvest."""
  t = Tracer(ring_capacity=16)
  t.instant("big", args={"blob": "x" * 4096})
  t.instant("after")
  chunk = t.drain_wire(64)
  assert [w[1] for w in chunk["events"]] == ["big"]
  assert [w[1] for w in t.drain_wire(64)["events"]] == ["after"]


def test_ingest_remote_rebases_and_clamps_monotonic():
  """Rebased child timestamps stay per-pid monotonic even when the
  re-estimated clock offset steps BACKWARDS between chunks."""
  parent = Tracer(ring_capacity=64)
  parent.ingest_remote(7, [["i", "a", "", 100.0, "main", None]],
                       offset_us=1000.0)
  # Offset re-estimated 500us lower: a naive rebase would send ts
  # backwards on pid 7; the clamp pins it at the high-water mark.
  parent.ingest_remote(7, [["i", "b", "", 110.0, "main", None]],
                       offset_us=500.0)
  parent.ingest_remote(7, [["i", "c", "", 2000.0, "main", None]],
                       offset_us=500.0)
  ts = [e["ts"] for e in parent.events()
        if e.get("ph") == "i" and e["pid"] == 7]
  assert ts == [1100.0, 1100.0, 2500.0]
  validate_trace(parent.events())


def test_ingest_remote_skips_malformed_events():
  parent = Tracer(ring_capacity=64)
  n = parent.ingest_remote(
      7, [["i", "good", "", 1.0, "main", None],
          ["i", "short"],                      # wrong arity
          "not-a-list",
          ["i", "good2", "", 2.0, "main", None]],
      offset_us=0.0)
  assert n == 2
  assert parent.remote_summary()[7]["events"] == 2


def test_merged_export_per_pid_tracks_and_metadata():
  """A drained child ring re-emerges in the parent export under the
  child's pid with its OWN track table (names preserved, tids
  re-assigned per pid) plus process_name metadata — and the merged
  trace passes the validator."""
  child = Tracer(ring_capacity=64)
  with child.span("prefill", cat="serving", track="serving/slot0"):
    child.instant("serving/first_token", cat="serving",
                  args={"uid": "7"})
  child.flow("t", 42, track="serving/requests")
  child.flow("f", 42, track="serving/requests")
  parent = Tracer(ring_capacity=64)
  parent.flow("s", 42, track="serving/requests")
  moved = 0
  while child.pending:  # tiny budget: force multi-sweep reassembly
    moved += parent.ingest_remote(
        4242, child.drain_wire(150)["events"], offset_us=1e6,
        label="replica0 worker (pid 4242)")
  assert moved == 5 and child.pending == 0
  events = validate_trace(parent.events())
  proc_names = {e["pid"]: e["args"]["name"] for e in events
                if e.get("ph") == "M" and e["name"] == "process_name"}
  assert proc_names[4242] == "replica0 worker (pid 4242)"
  remote_tracks = {e["args"]["name"] for e in events
                   if e.get("ph") == "M" and e["name"] == "thread_name"
                   and e["pid"] == 4242}
  assert {"serving/slot0", "serving/requests"} <= remote_tracks
  # The flow arcs across the process boundary: s on the parent pid,
  # t/f on the child pid, one shared id.
  (evs,) = _flows(events).values()
  assert [e["ph"] for e in evs] == ["s", "t", "f"]
  assert evs[0]["pid"] != evs[1]["pid"]


def test_close_remote_ends_dangling_spans_at_death():
  """A SIGKILLed child's harvested ring ends in open ``B`` events;
  close_remote synthesizes their ``E`` at the pid's last rebased
  timestamp (LIFO, tagged with the death reason), idempotently — so
  the merged trace validates and renders the victim's work ending at
  the kill."""
  parent = Tracer(ring_capacity=64)
  parent.ingest_remote(7, [
      ["B", "request 3", "serving.request", 100.0, "slot0", None],
      ["B", "decode", "serving", 120.0, "slot0", None],
      ["i", "tick", "", 130.0, "slot0", None],
  ], offset_us=0.0)
  with pytest.raises(ValueError, match="unclosed span"):
    validate_trace(parent.events())
  assert parent.close_remote(7, reason="killed") == 2
  events = validate_trace(parent.events())
  ends = [e for e in events if e["ph"] == "E"]
  assert [e["name"] for e in ends] == ["decode", "request 3"]
  assert all(e["ts"] == 130.0 for e in ends)
  assert all(e["args"]["finish_reason"] == "killed" for e in ends)
  assert parent.close_remote(7) == 0, "idempotent"


# ------------------------------------- validator: multi-process rules


def _base(pid, ts, ph="i", name="x", tid=0, **extra):
  ev = {"ph": ph, "name": name, "pid": pid, "tid": tid, "ts": ts}
  ev.update(extra)
  return ev


def test_validator_accepts_interleaved_pids_each_monotonic():
  """A merged trace interleaves processes whose clocks are only
  offset-aligned: global ts order across pids is NOT required, only
  per-pid monotonicity."""
  validate_trace([
      _base(0, 100.0), _base(7, 50.0), _base(0, 200.0),
      _base(7, 60.0)])  # pid0: 100,200; pid7: 50,60 — unsorted, valid


def test_validator_flags_per_pid_nonmonotonic():
  with pytest.raises(ValueError, match=r"not monotonic"):
    validate_trace([_base(7, 100.0), _base(0, 10.0), _base(7, 90.0)])


def test_validator_flags_flow_step_without_start():
  """A child pid's harvested ``t`` whose ``s`` never made it (or was
  emitted with a different id) is a broken arc, not a valid trace."""
  with pytest.raises(ValueError, match=r"no open flow start"):
    validate_trace([
        _base(0, 1.0, ph="s", name="flow", cat="serving", id=5),
        _base(7, 2.0, ph="t", name="flow", cat="serving", id=6),
        _base(0, 3.0, ph="f", name="flow", cat="serving", id=5)])


def test_validator_flags_flow_cat_mismatch():
  """Viewers match flows by category + id: a cross-process step that
  disagrees on cat silently severs the arc, so the validator names it."""
  with pytest.raises(ValueError, match=r"flows bind by cat \+ id"):
    validate_trace([
        _base(0, 1.0, ph="s", name="flow", cat="serving", id=5),
        _base(7, 2.0, ph="t", name="flow", cat="other", id=5),
        _base(0, 3.0, ph="f", name="flow", cat="serving", id=5)])


def test_validator_flags_duplicate_pid_track_metadata():
  """A merge bug that emits one pid's track table twice corrupts
  Perfetto's row labels."""
  meta = {"ph": "M", "name": "thread_name", "pid": 7, "tid": 3,
          "args": {"name": "serving/slot0"}}
  with pytest.raises(ValueError, match=r"duplicate thread_name"):
    validate_trace([meta, dict(meta)])
  # Same tid on DIFFERENT pids is two distinct tracks — fine.
  validate_trace([meta, {**meta, "pid": 8}])


# ------------------------------------------- report: hop decomposition


def test_report_hop_breakdown_columns():
  """Front-door instants turn into the hop columns: client-observed
  TTFT (request -> first byte), ingress (request -> router submit) and
  wire (engine first token -> first byte) — and traces WITHOUT them
  keep the old table shape."""
  uid = "r1"
  events = [
      _base(0, 100.0, name="frontdoor/request", args={"uid": uid}),
      _base(0, 200.0, name="serving/submit", args={"uid": uid}),
      _base(7, 300.0, ph="B", name="req r1", tid=5,
            cat="serving.request", args={"uid": uid}),
      _base(7, 310.0, ph="B", name="prefill", tid=5, cat="serving"),
      _base(7, 350.0, ph="E", name="prefill", tid=5, cat="serving"),
      _base(7, 350.0, name="serving/first_token", args={"uid": uid}),
      _base(7, 400.0, ph="E", name="req r1", tid=5,
            cat="serving.request", args={"finish_reason": "stop"}),
      _base(0, 460.0, name="frontdoor/first_byte", args={"uid": uid}),
  ]
  (row,) = report.request_timelines(events)
  assert row["queue_wait_us"] == 100.0
  assert row["ingress_us"] == 100.0
  assert row["client_ttft_us"] == 360.0
  assert row["wire_us"] == 110.0
  assert row["prefill_us"] == 40.0
  text = report.format_report(events)
  assert "fd-ttft" in text and "wire" in text
  assert "360us" in text
  # Engine-only trace: hop columns stay hidden.
  plain = report.format_report(events[2:-1])
  assert "fd-ttft" not in plain and "wire" not in plain


def test_report_inner_spans_keyed_by_pid_and_tid():
  """Two processes reuse the same tid for different tracks; a request's
  inner phase spans must only match within its OWN pid."""
  events = [
      _base(7, 100.0, ph="B", name="req a", tid=5,
            cat="serving.request", args={"uid": "a"}),
      # Same tid, same window, DIFFERENT pid: must not be attributed
      # to request "a".
      _base(8, 110.0, ph="B", name="prefill", tid=5, cat="serving"),
      _base(8, 150.0, ph="E", name="prefill", tid=5, cat="serving"),
      _base(7, 200.0, ph="E", name="req a", tid=5,
            cat="serving.request", args={"finish_reason": "stop"}),
  ]
  (row,) = report.request_timelines(events)
  assert row["prefill_us"] == 0.0 and row["prefill_chunks"] == 0


# --------------------------------------- the acceptance: real processes


@pytest.mark.quick
def test_process_sigkill_merged_trace_single_connected_flow(tmp_path):
  """ISSUE 20 acceptance: SIGKILL one of two process replicas
  mid-decode, then export ONE merged Perfetto trace — schema-valid
  with per-pid monotonic rebased timestamps — in which a failed-over
  request is a single connected flow spanning the parent and BOTH
  child pids."""
  config = _dist_config()
  epl.init(config)
  tracer = trace_lib.ensure_configured()
  prompts = _prompts(6)
  router = Router(num_replicas=2, config=config, factory=FACTORY,
                  num_slots=4, prefill_chunk=4)
  pids = [rep.child_pid for rep in router.replicas]
  for i, p in enumerate(prompts):
    assert router.submit(Request(uid=i, prompt=p, max_new_tokens=10))
  for _ in range(3):            # let decode get going on both children
    router.step()
  victim = router.replicas[0]
  assert victim.has_work, "victim must die MID-decode, not idle"
  victim_pid, survivor_pid = pids
  chaos.ProcessKiller(victim).kill()
  router.run()
  assert router.failovers >= 1
  assert victim.exit_signal == signal.SIGKILL
  assert set(router.finished) == set(range(len(prompts)))
  # Explicit drain of the survivor's ring remainder, then export.
  router.harvest_traces()
  assert router.router_counters()["trace_events_harvested"] > 0
  router.close()
  trace_path = str(tmp_path / "trace.json")
  assert tracer.export(trace_path)
  events = validate_trace(trace_path)

  event_pids = {e["pid"] for e in events if e.get("ph") != "M"}
  assert {0, victim_pid, survivor_pid} <= event_pids, \
      "merged trace must carry the parent and BOTH children"
  # The SIGKILL lost at most the victim's un-harvested tail: its admit
  # window DID ride earlier step-reply piggybacks.
  spanning = [fid for fid, evs in _flows(events).items()
              if {0, victim_pid, survivor_pid}
              <= {e["pid"] for e in evs}]
  assert spanning, "no failed-over flow touches parent + both children"
  for fid in spanning:
    phases = [e["ph"] for e in _flows(events)[fid]]
    assert phases[0] == "s" and phases[-1] == "f", (fid, phases)
  _assert_no_orphans(pids)


@pytest.mark.quick
def test_process_fault_free_harvest_bit_exact_clean_drain(tmp_path):
  """The fault-free guard + the satellite bugfix pin: with harvest
  fully enabled on ``transport=process``, streams are bit-identical to
  the fault-free oracle and the fused step compiled once — and a
  cleanly closed replica's spans ALL appear in the merged trace (the
  shutdown reply carries the ring remainder; no explicit harvest call
  needed)."""
  prompts = _prompts(4)
  oracle = _oracle_outputs(prompts)
  config = _dist_config()
  epl.init(config)
  tracer = trace_lib.ensure_configured()
  router = Router(num_replicas=1, config=config, factory=FACTORY,
                  num_slots=4, prefill_chunk=4)
  pid = router.replicas[0].child_pid
  for i, p in enumerate(prompts):
    assert router.submit(Request(uid=i, prompt=p, max_new_tokens=10))
  out = router.run()
  assert router.replicas[0].compile_count == 1, \
      "harvest must add zero recompiles"
  assert set(out) == set(oracle)
  for uid in oracle:
    np.testing.assert_array_equal(np.asarray(out[uid]), oracle[uid],
                                  err_msg=f"req {uid}")
  router.close()               # clean exit: shutdown reply flushes all
  trace_path = str(tmp_path / "trace.json")
  assert tracer.export(trace_path)
  events = validate_trace(trace_path)
  child_request_spans = {
      (e["args"] or {}).get("uid") for e in events
      if e.get("ph") == "B" and e.get("cat") == "serving.request"
      and e["pid"] == pid}
  assert child_request_spans == {str(i) for i in range(len(prompts))}, \
      "every request's child-side span must reach the merged trace"
  # Every started flow terminated — and each request's arc touches
  # both processes (s at the router, t/f on the child).
  for fid, evs in _flows(events).items():
    assert {e["pid"] for e in evs} == {0, pid}, fid
  _assert_no_orphans([pid])
