"""Resilience layer under injected faults (testing/chaos.py): crash-
consistent checkpoints with checksum fallback, the anomaly sentinel's
skip/rollback, IO retry, the step watchdog, and preemption round-trip
exactness.  `make chaos` runs this suite standalone."""

import json
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu import ops
from easyparallellibrary_tpu.parallel import (
    TrainState, create_sharded_train_state, make_train_step, parallelize)
from easyparallellibrary_tpu.runtime import resilience, saver
from easyparallellibrary_tpu.runtime.loop import fit
from easyparallellibrary_tpu.testing import chaos
from easyparallellibrary_tpu.utils.retry import retry_call


class Net(nn.Module):
  @nn.compact
  def __call__(self, x):
    return ops.Dense(1, parallel="none")(jnp.tanh(
        ops.Dense(8, parallel="none")(x)))


def _batch(seed=0):
  r = np.random.RandomState(seed)
  return {"x": jnp.asarray(r.randn(16, 4), jnp.float32),
          "y": jnp.asarray(r.randn(16, 1), jnp.float32)}


def _setup(config=None, sentinel=False):
  env = epl.init(config)
  mesh = epl.current_plan().build_mesh()
  model = Net()
  batch = _batch()

  def init_fn(rng):
    st = TrainState.create(apply_fn=model.apply,
                           params=model.init(rng, batch["x"])["params"],
                           tx=optax.adam(1e-2))
    return resilience.attach_sentinel(st) if sentinel else st

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))

  def loss_fn(params, b, rng):
    pred = model.apply({"params": params}, b["x"])
    return jnp.mean((pred - b["y"]) ** 2), {}

  step = make_train_step(loss_fn)
  if sentinel:
    step = resilience.guard_step(step)
  step = parallelize(step, mesh, shardings)
  return state, shardings, step, batch


# --------------------------------------------- crash-consistent saver --


def test_atomic_commit_layout_and_checksums(tmp_path):
  state, _, _, _ = _setup()
  root = str(tmp_path / "ck")
  path = saver.save_checkpoint(root, state.params, step=7)
  assert os.path.basename(path) == "step_00000007"
  assert not [d for d in os.listdir(root) if d.endswith(".tmp")]
  index = json.load(open(os.path.join(path, "index.json")))
  assert index["shards"] and all(
      set(e) >= {"file", "bytes", "sha256"} for e in index["shards"])
  ok, reason = saver.verify_checkpoint(path)
  assert ok, reason


def test_corrupt_newest_falls_back_and_quarantines(tmp_path):
  state, shardings, step, batch = _setup()
  root = str(tmp_path / "ck")
  p5 = saver.save_checkpoint(root, state.params, step=5)
  params5 = jax.tree_util.tree_map(np.asarray, nn.unbox(state.params))
  state, _ = step(state, batch, jax.random.PRNGKey(1))
  p9 = saver.save_checkpoint(root, state.params, step=9)
  # Bit-flip (size-preserving): only the checksum can catch this.
  chaos.corrupt_shard(p9, mode="flip")
  assert saver.latest_step(root) == 5
  # p9 was quarantined out of the chain by the scan above.
  assert not os.path.isdir(p9)
  assert any(d.endswith(".corrupt") for d in os.listdir(root))
  restored, rstep = saver.restore_checkpoint(root, target=state.params)
  assert rstep == 5
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
      nn.unbox(restored), params5)


def test_truncated_shard_detected_by_size(tmp_path):
  state, _, _, _ = _setup()
  root = str(tmp_path / "ck")
  saver.save_checkpoint(root, state.params, step=3)
  p6 = saver.save_checkpoint(root, state.params, step=6)
  chaos.corrupt_shard(p6, mode="truncate")
  ok, reason = saver.verify_checkpoint(p6)
  assert not ok and "size" in reason
  assert saver.latest_step(root) == 3


def test_truncated_or_missing_index_skipped(tmp_path):
  state, _, _, _ = _setup()
  root = str(tmp_path / "ck")
  saver.save_checkpoint(root, state.params, step=2)
  p4 = saver.save_checkpoint(root, state.params, step=4)
  p8 = saver.save_checkpoint(root, state.params, step=8)
  chaos.corrupt_index(p8, mode="truncate")
  chaos.corrupt_index(p4, mode="delete")
  assert saver.latest_step(root) == 2
  restored, rstep = saver.restore_checkpoint(root, target=state.params)
  assert rstep == 2


def test_all_candidates_corrupt_raises_clearly(tmp_path):
  state, _, _, _ = _setup()
  root = str(tmp_path / "ck")
  p1 = saver.save_checkpoint(root, state.params, step=1)
  chaos.corrupt_index(p1, mode="garbage")
  with pytest.raises(FileNotFoundError, match="VALID"):
    saver.restore_checkpoint(root, target=state.params)
  assert saver.latest_step(root) is None


def test_keep_last_retention(tmp_path):
  state, _, _, _ = _setup()
  root = str(tmp_path / "ck")
  for s in (1, 2, 3, 4, 5):
    saver.save_checkpoint(root, state.params, step=s, keep_last=2)
  steps = sorted(d for d in os.listdir(root) if d.startswith("step_"))
  assert steps == ["step_00000004", "step_00000005"]
  assert saver.latest_step(root) == 5


def test_stale_staging_dir_cleaned_and_ignored(tmp_path):
  state, _, _, _ = _setup()
  root = str(tmp_path / "ck")
  saver.save_checkpoint(root, state.params, step=1)
  # Fake a crash mid-save: a staging dir that never committed.
  os.makedirs(os.path.join(root, "step_00000002.tmp"))
  assert saver.latest_step(root) == 1       # .tmp is never a candidate
  saver.save_checkpoint(root, state.params, step=3)
  assert not [d for d in os.listdir(root) if d.endswith(".tmp")]


def test_legacy_flat_layout_still_restores(tmp_path):
  import shutil
  state, _, _, _ = _setup()
  root = str(tmp_path / "ck")
  path = saver.save_checkpoint(root, state.params, step=5)
  flat = str(tmp_path / "flat")
  os.makedirs(flat)
  for f in os.listdir(path):
    shutil.copy(os.path.join(path, f), os.path.join(flat, f))
  assert saver.latest_step(flat) == 5
  restored, rstep = saver.restore_checkpoint(flat, target=state.params)
  assert rstep == 5
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_array_equal(
          np.asarray(a), np.asarray(b)),
      nn.unbox(restored), nn.unbox(state.params))


def test_flat_legacy_coexists_with_step_dirs(tmp_path):
  """Upgrade path: a pre-chain FLAT checkpoint in the root must not
  shadow newer step_N checkpoints saved beside it — and it stays in the
  chain as the last-resort fallback."""
  import shutil
  state, _, _, _ = _setup()
  root = str(tmp_path / "ck")
  src = saver.save_checkpoint(root, state.params, step=3)
  for f in os.listdir(src):  # fake the legacy layout: files in the root
    shutil.copy(os.path.join(src, f), os.path.join(root, f))
  shutil.rmtree(src)
  assert saver.latest_step(root) == 3       # flat-only: still restores
  p5 = saver.save_checkpoint(root, state.params, step=5)
  assert saver.latest_step(root) == 5       # newer step dir wins
  chaos.corrupt_shard(p5, mode="flip")
  restored, rstep = saver.restore_checkpoint(root, target=state.params)
  assert rstep == 3                         # …and the flat one catches us


def test_fit_feeds_profiler_resilience_counters():
  from easyparallellibrary_tpu.profiler.profiler import StepProfiler
  state, shardings, step, batch = _setup()
  prof = StepProfiler(warmup=0)
  data = chaos.FlakyIterator([batch] * 4, fail_at=1, failures=2)
  state, _ = fit(step, state, data, num_steps=4, log_every=0,
                 profiler=prof)
  assert prof.io_retries == 2
  assert prof.summary().get("io_retries") == 2.0


def test_non_atomic_mode_still_validates(tmp_path):
  state, _, _, _ = _setup()
  root = str(tmp_path / "ck")
  path = saver.save_checkpoint(root, state.params, step=2, atomic=False)
  assert os.path.basename(path) == "step_00000002"
  ok, reason = saver.verify_checkpoint(path)
  assert ok, reason


# ------------------------------------------------------------- retry --


def test_retry_call_recovers_transient_and_respects_permanent():
  calls = chaos.flaky(lambda: "ok", failures=2)
  assert retry_call(calls, retries=3, backoff_s=0.0) == "ok"

  fails = chaos.flaky(lambda: "ok", failures=5)
  with pytest.raises(IOError):
    retry_call(fails, retries=2, backoff_s=0.0)

  # FileNotFoundError is deterministic — no retries burned on it.
  attempts = {"n": 0}

  def missing():
    attempts["n"] += 1
    raise FileNotFoundError("gone")

  with pytest.raises(FileNotFoundError):
    retry_call(missing, retries=3, backoff_s=0.0)
  assert attempts["n"] == 1


def test_fit_retries_transient_data_error(tmp_path):
  state, shardings, step, batch = _setup()
  data = chaos.FlakyIterator([batch] * 5, fail_at=2, failures=2)
  from easyparallellibrary_tpu.utils.metrics_writer import MetricsWriter
  path = str(tmp_path / "m.jsonl")
  with MetricsWriter(path) as w:
    state, _ = fit(step, state, data, num_steps=5, log_every=0,
                   metrics_writer=w)
  assert int(state.step) == 5
  lines = [json.loads(l) for l in open(path)]
  assert lines[-1]["io_retries"] == 2


def test_fit_exhausted_retries_reraises():
  state, shardings, step, batch = _setup()
  data = chaos.FlakyIterator([batch] * 5, fail_at=1, failures=99)
  with pytest.raises(IOError):
    fit(step, state, data, num_steps=5, log_every=0)


def test_flops_profiler_surfaces_resilience_counters():
  from easyparallellibrary_tpu.profiler.flops import FlopsProfiler
  prof = FlopsProfiler(flops_per_step=1e9, every_n_steps=2)
  prof.note_bad_step()
  prof.note_retry(3)
  stats = None
  for _ in range(3):
    stats = prof.step() or stats
  assert stats is not None
  assert stats["bad_steps"] == 1.0 and stats["io_retries"] == 3.0


# ---------------------------------------------------- anomaly sentinel --


def test_sentinel_skips_nan_update_exactly(tmp_path):
  """A NaN batch at step K is a true no-op: the trajectory afterwards is
  bit-identical to a run that never saw the bad batch."""
  b1, b3 = _batch(1), _batch(3)
  state, shardings, step, _ = _setup(sentinel=True)
  bad = chaos.nan_batch(b1)
  state, metrics = fit(step, state, [b1, bad, b3], num_steps=3,
                       log_every=0)
  assert int(state.step) == 2              # the poisoned step didn't count
  assert int(metrics["bad_steps"]) == 0    # last step was clean
  assert int(metrics["bad_steps_total"]) == 1
  poisoned = jax.tree_util.tree_map(np.asarray,
                                    jax.device_get(nn.unbox(state.params)))

  state2, _, step2, _ = _setup(sentinel=True)
  state2, _ = fit(step2, state2, [b1, b3], num_steps=2, log_every=0)
  clean = jax.tree_util.tree_map(np.asarray,
                                 jax.device_get(nn.unbox(state2.params)))
  jax.tree_util.tree_map(np.testing.assert_array_equal, poisoned, clean)


def test_sentinel_metrics_reach_writer(tmp_path):
  from easyparallellibrary_tpu.utils.metrics_writer import MetricsWriter
  state, shardings, step, batch = _setup(sentinel=True)
  path = str(tmp_path / "m.jsonl")
  with MetricsWriter(path) as w:
    fit(step, state, [batch, chaos.nan_batch(batch), batch], num_steps=3,
        log_every=0, metrics_writer=w)
  lines = [json.loads(l) for l in open(path)]
  assert [l["bad_steps"] for l in lines] == [0.0, 1.0, 0.0]
  assert lines[-1]["bad_steps_total"] == 1.0
  assert [l["update_skipped"] for l in lines] == [0.0, 1.0, 0.0]


def test_sentinel_single_program_zero_host_sync():
  """Acceptance: the guard lives inside the ONE jitted step — no second
  compiled program, and no device->host transfer per step."""
  state, shardings, step, batch = _setup(sentinel=True)
  state, _ = step(state, batch, jax.random.PRNGKey(0))  # compile
  with jax.transfer_guard_device_to_host("disallow"):
    for i in range(5):
      state, metrics = step(state, batch, jax.random.PRNGKey(i))
  assert step.jitted._cache_size() == 1
  assert int(state.step) == 6


def test_trainer_sentinel_composes_with_amp_loss_scale():
  """fp16 AMP + sentinel: DynamicLossScale keeps the scale semantics,
  the sentinel contributes the counters — one step function."""
  from easyparallellibrary_tpu.runtime.trainer import (
      build_train_step, create_train_state)
  env = epl.init(epl.Config({
      "amp": {"level": "O1", "compute_dtype": "fp16",
              "loss_scale": "dynamic"},
      "resilience": {"sentinel": True}}))
  model = Net()
  batch = _batch()
  params = model.init(jax.random.PRNGKey(0), batch["x"])["params"]

  def loss_fn(p, b, rng):
    pred = model.apply({"params": p}, b["x"])
    return jnp.mean((pred - b["y"]) ** 2), {}

  state = create_train_state(model.apply, params, optax.adam(1e-2))
  assert state.sentinel is not None
  step = jax.jit(build_train_step(loss_fn))
  state, m = step(state, batch, jax.random.PRNGKey(1))
  assert int(m["bad_steps"]) == 0 and "loss_scale" in m
  state, m = step(state, chaos.nan_batch(batch), jax.random.PRNGKey(2))
  assert int(m["bad_steps"]) == 1
  assert float(m["update_skipped"]) == 1.0
  state, m = step(state, batch, jax.random.PRNGKey(3))
  assert int(m["bad_steps"]) == 0 and int(m["bad_steps_total"]) == 1
  assert np.isfinite(
      np.asarray(jax.tree_util.tree_leaves(state.params)[0])).all()


def test_rollback_recovers_from_persistent_nans(tmp_path):
  """Steps 4..6 are poisoned on first encounter; max_bad_steps=2 trips
  the sentinel, fit rolls back to the step-4 checkpoint, replays (clean
  this time), and finishes the run."""
  cfg = epl.Config({"resilience": {"max_bad_steps": 2}})
  state, shardings, step, batch = _setup(cfg, sentinel=True)
  ckpt = str(tmp_path / "ck")
  starts = []

  injector = chaos.NaNInjector(lambda s: _batch(s), bad_steps=(4, 5, 6),
                               num_steps=8)

  def factory(start_step=0):
    starts.append(start_step)
    return injector(start_step)

  state, metrics = fit(step, state, factory, num_steps=8,
                       checkpoint_dir=ckpt, checkpoint_every=4,
                       log_every=0, shardings=shardings)
  # Poisoned steps 4 and 5 tripped max_bad_steps=2 -> rollback to the
  # step-4 checkpoint; the replay sees clean data for 4 and 5 but step 6
  # is poisoned on ITS first encounter and gets skipped (one suppressed
  # update), so the state advances 7 times over 8 loop steps.
  assert int(state.step) == 7
  assert injector.poisoned == [4, 5, 6]     # faults really happened
  assert starts == [0, 4]                   # stream rewound to the rollback
  assert saver.latest_step(ckpt) == 8
  params = jax.tree_util.tree_leaves(jax.device_get(state.params))
  assert all(np.isfinite(np.asarray(p)).all() for p in params)


def test_rollback_off_fails_fast(tmp_path):
  cfg = epl.Config({"resilience": {"max_bad_steps": 2, "rollback": False}})
  state, shardings, step, batch = _setup(cfg, sentinel=True)
  data = [batch, batch, chaos.nan_batch(batch), chaos.nan_batch(batch),
          batch, batch]
  with pytest.raises(RuntimeError, match="non-finite"):
    fit(step, state, data, num_steps=6, log_every=0,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
        shardings=shardings)


def test_persistent_fault_gives_up_after_rollback_cap(tmp_path):
  """A DETERMINISTIC fault (same step poisoned on every replay) must hit
  the consecutive-rollback cap and raise, not replay forever — and a
  clean replayed prefix must not reset the cap."""
  cfg = epl.Config({"resilience": {"max_bad_steps": 2}})
  state, shardings, step, batch = _setup(cfg, sentinel=True)
  ckpt = str(tmp_path / "ck")

  # Poison every draw of steps >= 4, on every replay (once=False).
  injector = chaos.NaNInjector(lambda s: _batch(s), bad_steps=(4, 5, 6, 7),
                               num_steps=8, once=False)
  with pytest.raises(RuntimeError, match="not transient"):
    fit(step, state, lambda start_step=0: injector(start_step),
        num_steps=8, checkpoint_dir=ckpt, checkpoint_every=4,
        log_every=0, shardings=shardings)
  # 1 initial + MAX_CONSECUTIVE_ROLLBACKS replays of the same window.
  replays = injector.poisoned.count(4)
  assert replays == resilience.MAX_CONSECUTIVE_ROLLBACKS + 1


def test_fit_refuses_fresh_start_over_corrupt_checkpoints(tmp_path):
  """All-corrupt checkpoint dir: resuming must raise, not silently
  retrain from step 0 — and a root holding only quarantined dirs (after
  a restart) must refuse too."""
  state, shardings, step, batch = _setup()
  root = str(tmp_path / "ck")
  p1 = saver.save_checkpoint(root, state.params, step=1)
  chaos.corrupt_index(p1, mode="garbage")
  with pytest.raises(RuntimeError, match="refusing to start fresh"):
    fit(step, state, [batch], num_steps=3, log_every=0,
        checkpoint_dir=root, shardings=shardings)
  # The refusal quarantined the candidate; a restart still refuses.
  assert saver.has_quarantined(root)
  state2, shardings2, step2, _ = _setup()
  with pytest.raises(RuntimeError, match="refusing to start fresh"):
    fit(step2, state2, [batch], num_steps=3, log_every=0,
        checkpoint_dir=root, shardings=shardings2)


def test_fit_permanent_error_mid_retry_not_retried():
  state, shardings, step, batch = _setup()
  errors = [IOError("transient blip"), FileNotFoundError("really gone")]

  class Flaky2:
    def __init__(self):
      self.attempts = 0
    def __iter__(self):
      return self
    def __next__(self):
      if errors:
        self.attempts += 1
        raise errors.pop(0)
      return batch

  data = Flaky2()
  with pytest.raises(FileNotFoundError):
    fit(step, state, data, num_steps=3, log_every=0)
  assert data.attempts == 2                 # no retries burned after FNF


def test_nonfinite_report_names_bad_leaves():
  from easyparallellibrary_tpu.runtime.amp import nonfinite_report
  tree = {"a": {"w": np.array([1.0, np.nan, np.inf]),
                "b": np.ones(3)},
          "n": np.array([1, 2], np.int32)}
  report = nonfinite_report(tree)
  assert report == {"a/w": 2}


def test_lr_backoff_via_inject_hyperparams():
  tx = optax.inject_hyperparams(optax.sgd)(learning_rate=0.5)
  opt_state = tx.init({"w": jnp.ones((2,))})
  new_state, applied = resilience.backoff_learning_rate(opt_state, 0.5)
  assert applied
  assert float(new_state.hyperparams["learning_rate"]) == 0.25

  plain = optax.adam(1e-3).init({"w": jnp.ones((2,))})
  same, applied = resilience.backoff_learning_rate(plain, 0.5)
  assert not applied


# ----------------------------------------------------------- watchdog --


def test_watchdog_fires_and_disarms():
  import time as _time
  fired = []
  dog = resilience.StepWatchdog(0.05, on_timeout=fired.append)
  dog.arm(7)
  _time.sleep(0.3)
  assert fired == [7] and dog.timeouts_fired == 1
  dog.arm(8)
  dog.disarm()
  _time.sleep(0.15)
  assert fired == [7]                       # disarm cancelled it
  dog.close()


def test_fit_watchdog_logs_slow_step(tmp_path):
  import logging
  import time as _time
  from easyparallellibrary_tpu.utils.logging import get_logger
  cfg = epl.Config({"resilience": {"step_timeout_s": 0.1}})
  state, shardings, step, batch = _setup(cfg)

  class SlowOnce:
    def __init__(self):
      self.n = 0
    def __iter__(self):
      return self
    def __next__(self):
      self.n += 1
      if self.n == 2:
        _time.sleep(0.4)
      return batch

  records = []
  handler = logging.Handler()
  handler.emit = records.append
  logger = get_logger()
  logger.addHandler(handler)
  try:
    state, _ = fit(step, state, SlowOnce(), num_steps=3, log_every=0)
  finally:
    logger.removeHandler(handler)
  assert int(state.step) == 3
  assert any("watchdog" in r.getMessage() for r in records)


# --------------------------------------------------------- preemption --


def test_sigterm_handler_restored_after_step_exception():
  state, shardings, step, batch = _setup()
  mine = lambda *a: None
  prev = signal.signal(signal.SIGTERM, mine)
  try:
    def boom(st, b, rng):
      raise ValueError("step exploded")

    with pytest.raises(ValueError):
      fit(boom, state, [batch], num_steps=3, log_every=0,
          checkpoint_dir="/tmp/does-not-matter-never-written")
    # fit must have put OUR handler back despite the escaping exception.
    assert signal.getsignal(signal.SIGTERM) is mine
  finally:
    signal.signal(signal.SIGTERM, prev)


def test_keyboard_interrupt_saves_final_checkpoint(tmp_path):
  state, shardings, step, batch = _setup()
  ckpt = str(tmp_path / "ck")

  class InterruptAt:
    def __init__(self, n):
      self.n, self.i = n, 0
    def __iter__(self):
      return self
    def __next__(self):
      self.i += 1
      if self.i > self.n:
        raise KeyboardInterrupt
      return batch

  with pytest.raises(KeyboardInterrupt):
    fit(step, state, InterruptAt(3), num_steps=10, checkpoint_dir=ckpt,
        log_every=0, shardings=shardings)
  assert saver.latest_step(ckpt) == 3


@pytest.mark.quick
def test_preemption_roundtrip_bit_exact(tmp_path):
  """SIGTERM mid-fit → checkpoint → resume: final params AND opt_state
  are bit-identical to the uninterrupted run."""
  batches = [_batch(s) for s in range(6)]

  def snap(st):
    return jax.tree_util.tree_map(
        np.asarray, jax.device_get(
            {"params": nn.unbox(st.params), "opt": st.opt_state}))

  state, shardings, step, _ = _setup()
  state, _ = fit(step, state, batches, num_steps=6, log_every=0,
                 shardings=shardings)
  uninterrupted = snap(state)

  class PreemptingStream:
    """Yields the deterministic batch sequence; delivers a real SIGTERM
    while fetching the batch for step 3 (first pass only)."""
    def __init__(self):
      self.calls = []
    def __call__(self, start_step=0):
      self.calls.append(start_step)
      def gen():
        for i, b in enumerate(batches[start_step:]):
          if start_step == 0 and i == 3:
            os.kill(os.getpid(), signal.SIGTERM)
          yield b
      return gen()

  ckpt = str(tmp_path / "ck")
  stream = PreemptingStream()
  state2, shardings2, step2, _ = _setup()
  with pytest.raises(SystemExit):
    fit(step2, state2, stream, num_steps=6, checkpoint_dir=ckpt,
        log_every=0, shardings=shardings2)
  saved = saver.latest_step(ckpt)
  assert saved is not None and 3 <= saved <= 5

  state3, shardings3, step3, _ = _setup()
  state3, _ = fit(step3, state3, stream, num_steps=6, checkpoint_dir=ckpt,
                  log_every=0, shardings=shardings3)
  assert int(state3.step) == 6
  assert stream.calls[-1] == saved          # input stream resumed in place
  resumed = snap(state3)
  jax.tree_util.tree_map(np.testing.assert_array_equal,
                         uninterrupted, resumed)
