"""Auto-parallel end-to-end: config -> planner -> partitioned pipeline ->
training (reference analog: epl/parallel/hooks.py:129-135 triggering
AutoStageGenerator from the build, tests/auto_parallel_test.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import optax

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import GPT, GPTConfig, auto_parallel_gpt
from easyparallellibrary_tpu.models.gpt import (
    gpt_loss, make_gpt_train_step, stage_layout)


def _base(**kw):
  base = dict(vocab_size=2048, num_layers=6, num_heads=4, d_model=32,
              d_ff=64, max_seq_len=16, dtype=jnp.float32)
  base.update(kw)
  return GPTConfig(**base)


def test_auto_parallel_derives_stage_plan():
  """Planner output lands in stage_plan: even models get the even split,
  uneven models the min-max-balanced uneven counts."""
  epl.init(epl.Config({"auto.auto_parallel": True,
                       "pipeline.num_stages": 4,
                       "pipeline.num_micro_batch": 4}))
  even = auto_parallel_gpt(_base(num_layers=8))
  assert even.cfg.pipeline_stages == 4
  assert even.cfg.num_micro_batch == 4
  assert even.cfg.stage_plan == (2, 2, 2, 2)

  uneven = auto_parallel_gpt(_base(num_layers=7))
  plan = uneven.cfg.stage_plan
  assert sum(plan) == 7 and len(plan) == 4 and min(plan) >= 1
  assert max(plan) == 2  # min-max balance: no stage hoards blocks


def test_auto_parallel_rejects_too_many_stages():
  import pytest
  epl.init(epl.Config({"auto.auto_parallel": True,
                       "pipeline.num_stages": 4}))
  with pytest.raises(ValueError):
    auto_parallel_gpt(_base(num_layers=3))


def test_auto_parallel_off_passthrough():
  epl.init()  # auto off by default
  model = auto_parallel_gpt(_base())
  assert model.cfg.pipeline_stages == 1
  assert model.cfg.stage_plan is None


@pytest.mark.slow
def test_auto_partitioned_gpt_trains_and_matches_manual():
  """VERDICT done-criterion: auto-partitioned GPT with uneven block
  weights trains; its loss matches the manually partitioned model with
  the same plan, and the sequential ground truth."""
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state, parallelize)

  env = epl.init(epl.Config({"auto.auto_parallel": True,
                             "pipeline.num_stages": 4,
                             "pipeline.num_micro_batch": 4}))
  mesh = env.cluster.build_mesh(stage=4)
  auto_model = auto_parallel_gpt(_base(num_layers=7))
  plan = auto_model.cfg.stage_plan
  assert sorted(plan) == [1, 2, 2, 2]  # the interesting (uneven) case

  manual = GPT(GPTConfig(**{**auto_model.cfg.__dict__}))  # same plan
  seq = GPT(GPTConfig(**{**auto_model.cfg.__dict__,
                         "pipeline_debug_sequential": True}))

  ids = jnp.asarray(np.random.RandomState(0).randint(0, 2048, (16, 17)),
                    jnp.int32)
  params = auto_model.init(jax.random.PRNGKey(0), ids[:, :-1])["params"]
  l_auto, _ = jax.jit(lambda p: gpt_loss(auto_model, p, {"ids": ids}))(params)
  l_manual, _ = jax.jit(lambda p: gpt_loss(manual, p, {"ids": ids}))(params)
  l_seq, _ = jax.jit(lambda p: gpt_loss(seq, p, {"ids": ids}))(params)
  np.testing.assert_allclose(float(l_auto), float(l_manual), rtol=1e-6)
  np.testing.assert_allclose(float(l_auto), float(l_seq), rtol=1e-5)

  def init_fn(rng):
    return TrainState.create(
        apply_fn=auto_model.apply,
        params=auto_model.init(rng, ids[:, :-1])["params"],
        tx=optax.adam(1e-2))

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  step = parallelize(make_gpt_train_step(auto_model), mesh, shardings)
  losses = []
  for i in range(6):
    state, m = step(state, {"ids": ids}, jax.random.PRNGKey(i))
    losses.append(float(m["loss"]))
  assert losses[-1] < losses[0]


def test_stage_plan_validation():
  import pytest
  with pytest.raises(ValueError):
    stage_layout(6, 2, stage_plan=(5, 2))   # sums to 7
  with pytest.raises(ValueError):
    stage_layout(6, 2, stage_plan=(6, 0))   # zero-count stage
  with pytest.raises(ValueError):
    stage_layout(6, 3, stage_plan=(3, 3))   # wrong length
  assert stage_layout(6, 2, stage_plan=(3, 3)) == (3, None)
  assert stage_layout(6, 2, stage_plan=(4, 2)) == (4, (4, 2))
