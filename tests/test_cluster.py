"""Cluster / layout / mesh tests (reference analog: tests/cluster_test*.py)."""

import jax
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu import constants


def test_eight_virtual_devices():
  assert len(jax.devices()) == 8


def test_all_layout_pure_dp():
  env = epl.init(layout="all")
  mesh = env.cluster.build_mesh()
  sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
  assert sizes[constants.DATA_AXIS] == 8
  assert all(sizes[a] == 1 for a in mesh.axis_names
             if a != constants.DATA_AXIS)


def test_auto_layout_infers_data():
  # Reference: replicas = total / Σ per-stage device_count
  # (epl/cluster.py:150-159).
  env = epl.init()
  mesh = env.cluster.build_mesh(stage=2, model=2)
  sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
  assert sizes[constants.STAGE_AXIS] == 2
  assert sizes[constants.MODEL_AXIS] == 2
  assert sizes[constants.DATA_AXIS] == 2
  assert mesh.axis_names == constants.MESH_AXES


def test_auto_layout_indivisible_raises():
  env = epl.init()
  with pytest.raises(ValueError):
    env.cluster.build_mesh(stage=3)


def test_specific_layout_from_config():
  env = epl.init(epl.Config({"cluster.mesh_shape": "stage:2,data:2,model:2"}))
  mesh = env.cluster.build_mesh()
  sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
  assert (sizes[constants.STAGE_AXIS], sizes[constants.DATA_AXIS],
          sizes[constants.MODEL_AXIS]) == (2, 2, 2)


def test_specific_layout_bad_shape():
  env = epl.init(epl.Config({"cluster.mesh_shape": "stage:3,data:2"}))
  with pytest.raises(ValueError):
    env.cluster.build_mesh()


def test_virtual_devices_per_stage():
  env = epl.init()
  env.cluster.build_mesh(stage=4)
  vds = env.cluster.virtual_devices
  assert len(vds) == 4
  assert all(vd.num_devices == 2 for vd in vds)
  ids = [d.id for vd in vds for d in vd.devices]
  assert sorted(ids) == list(range(8))


def test_mesh_devices_unique():
  env = epl.init()
  mesh = env.cluster.build_mesh(model=8)
  assert len({d.id for d in mesh.devices.flatten()}) == 8
