"""Replicated serving control plane (ISSUE 8): health-checked router,
bit-exact replica failover, graceful drain, replica-kill chaos.

The acceptance contract (`make chaos-router`): with 2+ replicas,
killing one mid-decode loses ZERO non-shed requests — every in-flight
request on the dead replica finishes on a survivor with a greedy token
stream bit-identical to the single-engine ``generate(use_cache=True)``
oracle, and the survivor's fused-step compile count stays 1 throughout
(failover is a prefix replay — no new shapes).  Graceful drain migrates
or completes a replica's load within ``drain_timeout_s`` and rejoin
resumes admission warm.  The heavyweight chaos episodes are
``slow``-marked (tier-1 window budget — ROADMAP); ``make chaos-router``
runs them all.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.models.gpt import generate
from easyparallellibrary_tpu.observability.registry import MetricRegistry
from easyparallellibrary_tpu.observability.report import (
    fleet_rollup, format_fleet)
from easyparallellibrary_tpu.profiler.serving import (
    ServingStats, fleet_summary)
from easyparallellibrary_tpu.serving import (
    ContinuousBatchingEngine, FCFSScheduler, ReplicaHealth, Request,
    Router)
from easyparallellibrary_tpu.serving.scheduler import FinishedRequest
from easyparallellibrary_tpu.testing import chaos
from easyparallellibrary_tpu.utils.metrics_writer import MetricsWriter

TINY = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                 d_ff=64, max_seq_len=32, dtype=jnp.float32)


def _model_and_params(cfg=TINY, seed=0):
  model = GPT(cfg)
  params = model.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, 4), jnp.int32))["params"]
  return model, params


def _prompts(lengths, vocab=64, seed=0):
  r = np.random.RandomState(seed)
  return [r.randint(0, vocab, (n,)).astype(np.int32) for n in lengths]


def _oracle(model, params, prompt, max_new):
  return np.asarray(
      generate(model, params, jnp.asarray(prompt)[None], max_new))[0]


def _router_config(**router):
  return epl.Config({"serving": {"router": router}})


class FakeClock:
  def __init__(self, t: float = 0.0):
    self.t = t

  def __call__(self) -> float:
    return self.t

  def advance(self, dt: float):
    self.t += dt


class FakeReplica:
  """Duck-typed replica for pure routing-policy tests (no device)."""

  def __init__(self, index, load=0, num_slots=4):
    self.index = index
    self._load = load
    self.num_slots = num_slots
    self.submitted = []
    self.restored = []
    self.finished = {}
    self.snaps = []
    self.stats = None
    self.accept = True
    self.watchdog_timeouts = 0
    self.bad_steps = 0
    self.itl_ewma_s = 0.0
    self.has_work = False

  def submit(self, req):
    if not self.accept:
      self.finished[req.uid] = FinishedRequest(
          uid=req.uid, tokens=np.asarray(req.prompt, np.int32),
          new_tokens=0, finish_reason="shed")
      return False
    self.submitted.append(req.uid)
    self._load += 1
    return True

  def cancel(self, uid):
    return False

  def step(self):
    return []

  @property
  def load(self):
    return self._load

  @property
  def queue_depth(self):
    return 0

  @property
  def num_active(self):
    return self._load

  def snapshot_requests(self):
    return list(self.snaps)

  def evacuate(self):
    snaps, self.snaps = self.snaps, []
    self.has_work = False
    return snaps

  def restore_request(self, snap, front=False):
    self.restored.append(snap["request"]["uid"])
    return snap["request"]["uid"]

  def close(self):
    pass


def _fake_router(loads, clock=None, **router_conf):
  clock = clock or FakeClock()
  reps = [FakeReplica(i, load=l) for i, l in enumerate(loads)]
  r = Router(replicas=reps, config=_router_config(**router_conf),
             clock=clock)
  return r, reps, clock


# ------------------------------------------------------- health machine


def test_replica_health_state_machine():
  """healthy -> suspect -> down by heartbeat age; a clean beat clears
  suspect; a dirty beat (watchdog timeout / new bad steps / over-SLO
  ITL) marks suspect even with a fresh heartbeat."""
  clock = FakeClock()
  h = ReplicaHealth(suspect_after=1.0, down_after=3.0, heartbeat_s=0.5,
                    itl_slo_s=0.01, clock=clock)
  assert h.state == "healthy" and h.routable
  clock.advance(1.5)
  assert h.observe() == "suspect" and not h.routable
  h.beat()                                   # clean beat recovers
  assert h.state == "healthy"
  h.beat(watchdog_timeouts=1)                # dirty: answered but hung
  assert h.state == "suspect"
  h.beat()
  assert h.state == "healthy"
  h.beat(bad_steps=2)                        # new bad steps: dirty
  assert h.state == "suspect"
  h.beat(bad_steps=2)                        # same cumulative count: clean
  assert h.state == "healthy"
  h.beat(itl_s=0.5)                          # over SLO: suspect
  assert h.state == "suspect"
  clock.advance(4.0)
  assert h.observe() == "down"
  assert not h.routable and h.trips == 1
  clock.advance(100.0)
  assert h.observe() == "down"               # down is sticky


def test_replica_health_breaker_backoff():
  """Each trip to down doubles the probe hold-out (capped); rejoin is
  refused before the cooldown, allowed after (or with force=True), and
  note_stable forgives one trip."""
  clock = FakeClock()
  h = ReplicaHealth(suspect_after=1.0, down_after=2.0, clock=clock)
  h.mark_down("chaos")
  assert h.trips == 1 and h.cooldown_s() == 2.0
  assert not h.can_probe() and not h.rejoin()
  clock.advance(2.5)
  assert h.can_probe() and h.rejoin() and h.state == "healthy"
  h.mark_down("chaos again")
  assert h.trips == 2 and h.cooldown_s() == 4.0
  clock.advance(2.5)
  assert not h.can_probe()                   # doubled hold-out binds
  assert h.rejoin(force=True)                # operator override
  h.note_stable()
  assert h.trips == 1
  # Drain is not a failure: no breaker trip, rejoin unconditional.
  h.drain()
  assert h.state == "draining" and not h.routable
  assert h.rejoin() and h.state == "healthy" and h.trips == 1


def test_replica_health_validation():
  with pytest.raises(ValueError, match="suspect_after"):
    ReplicaHealth(suspect_after=5.0, down_after=1.0)
  with pytest.raises(ValueError, match="heartbeat_s"):
    ReplicaHealth(heartbeat_s=0.0)


def test_router_config_validation():
  with pytest.raises(ValueError, match="replicas"):
    _router_config(replicas=0)
  with pytest.raises(ValueError, match="suspect_after"):
    _router_config(suspect_after=5.0, down_after=1.0)
  with pytest.raises(ValueError, match="heartbeat_s"):
    _router_config(heartbeat_s=0.0)
  with pytest.raises(ValueError, match="drain_timeout_s"):
    _router_config(drain_timeout_s=-1.0)


# -------------------------------------------------- snapshot / restore


def test_request_snapshot_restore_round_trip():
  """Request.snapshot()/restore() is lossless through JSON — sampling
  knobs, lifecycle fields and the speculative opt-out flag included."""
  req = Request(uid="r1", prompt=np.asarray([3, 1, 4], np.int32),
                max_new_tokens=7, temperature=0.8, top_k=5, top_p=0.9,
                stop_token=2, seed=11, speculative=False,
                deadline_s=4.0, ttft_budget_s=1.5, priority="latency")
  back = Request.restore(json.loads(json.dumps(req.snapshot())))
  np.testing.assert_array_equal(back.prompt, req.prompt)
  for f in ("uid", "max_new_tokens", "temperature", "top_k", "top_p",
            "stop_token", "seed", "speculative", "deadline_s",
            "ttft_budget_s", "priority"):
    assert getattr(back, f) == getattr(req, f), f
  # None-valued optionals survive too.
  again = Request.restore(json.loads(json.dumps(Request(
      uid=0, prompt=np.asarray([1], np.int32),
      max_new_tokens=1).snapshot())))
  assert again.seed is None and again.speculative is None


def test_scheduler_snapshot_evacuate_restore_mid_flight():
  """Scheduler-level migration currency: evacuate() drains queued AND
  in-flight requests into JSON-serializable snapshots; restore on a
  FRESH scheduler replays the committed prefix with the tok_index fold
  intact (the bit-exactness precondition)."""
  clock = FakeClock()
  sched = FCFSScheduler(num_slots=1, prefill_chunk=4, max_seq_len=32,
                        clock=clock)
  a, b = _prompts((3, 5), seed=1)
  sched.submit(Request(uid="fly", prompt=a, max_new_tokens=8))
  sched.plan_step()                              # "fly" takes the slot
  sched.commit(np.asarray([9], np.int32))        # prefix done + 1 token
  sched.submit(Request(uid="wait", prompt=b, max_new_tokens=4))
  snaps = json.loads(json.dumps(sched.evacuate()))
  assert [s["request"]["uid"] for s in snaps] == ["fly", "wait"]
  assert snaps[0]["generated"] == [9]
  assert snaps[0]["first_token_emitted"] is True
  assert snaps[1]["generated"] == []
  assert not sched.has_work and sched.allocator.num_free == 1
  dest = FCFSScheduler(num_slots=1, prefill_chunk=4, max_seq_len=32,
                       clock=clock)
  for snap in reversed(snaps):
    dest.restore_request(snap, front=True)
  assert [e.uid for e in dest.pending] == ["fly", "wait"]
  plan = dest.plan_step()                        # replay = chunked prefill
  np.testing.assert_array_equal(plan.tokens[0, :4], list(a) + [9])
  assert plan.tok_index[0] == 1                  # PRNG fold continues
  dest.commit(np.asarray([5], np.int32))
  assert dest.active[0].generated == [9, 5]


def test_snapshot_restore_preserves_sampled_stream():
  """The PRNG fold-by-committed-token-index contract end to end: a
  SAMPLED request interrupted mid-decode, snapshotted, JSON'd and
  restored into the same engine finishes with a stream bit-identical to
  the uninterrupted run (the key re-derives from the seed; the fold
  index is the committed count — nothing else is state)."""
  epl.init()
  model, params = _model_and_params()
  (p,) = _prompts((5,), seed=3)

  def req(uid):
    return Request(uid=uid, prompt=p, max_new_tokens=8,
                   temperature=0.8, top_k=8, seed=42)

  eng = ContinuousBatchingEngine(model, params, num_slots=1,
                                 prefill_chunk=4)
  eng.submit(req("ref"))
  ref = eng.run()["ref"]
  eng.submit(req("mig"))
  for _ in range(4):                     # prefill + a few decode steps
    eng.step()
  (snap,) = json.loads(json.dumps(eng.snapshot_requests()))
  assert 0 < len(snap["generated"]) < 8, "interrupt must be mid-decode"
  assert eng.evacuate() and not eng.has_work
  eng.restore_request(snap)
  out = eng.run()
  np.testing.assert_array_equal(out["mig"], ref)
  assert eng._step_fn._cache_size() == 1


# ---------------------------------------------- proactive preemption


def _paged_sched(clock, num_slots=2, **kw):
  kw.setdefault("block_size", 4)
  kw.setdefault("num_blocks", 32)
  kw.setdefault("token_budget", 8)
  return FCFSScheduler(num_slots=num_slots, prefill_chunk=4,
                       max_seq_len=16, clock=clock, **kw)


def test_proactive_preemption_admits_latency_class():
  """A latency-class arrival finding every slot held by throughput
  requests evicts the YOUNGEST one eagerly at admission (not waiting
  for pool exhaustion); the victim requeues with its prefix intact and
  the eviction is counted as proactive, not exhaustion."""
  clock = FakeClock()
  sched = _paged_sched(clock)
  a, b, c = _prompts((3, 3, 3), seed=2)
  sched.submit(Request(uid="t0", prompt=a, max_new_tokens=8))
  sched.submit(Request(uid="t1", prompt=b, max_new_tokens=8))
  sched.plan_step()
  sched.commit(np.asarray([[1], [1]], np.int32))
  sched.submit(Request(uid="lat", prompt=c, max_new_tokens=4,
                       priority="latency"))
  sched.plan_step()
  uids = {s.req.uid for s in sched.active.values()}
  assert "lat" in uids and "t0" in uids and "t1" not in uids
  assert sched.proactive_preemptions == 1
  assert sched.preemptions == 0            # not an exhaustion event
  assert sched.pending[0].uid == "t1"      # committed prefix carried
  assert sched.pending[0].prefix_len == len(b) + 1


def test_proactive_preemption_never_evicts_latency_or_unpaged():
  """Eligibility: an older latency-class slot is never evicted for a
  younger latency arrival (admission-seq ordering), and the contiguous
  engine (no blocks to reclaim) never preempts proactively."""
  clock = FakeClock()
  sched = _paged_sched(clock, num_slots=1)
  a, b = _prompts((3, 3), seed=4)
  sched.submit(Request(uid="lat0", prompt=a, max_new_tokens=8,
                       priority="latency"))
  sched.plan_step()
  sched.commit(np.asarray([[1]], np.int32))
  sched.submit(Request(uid="lat1", prompt=b, max_new_tokens=4,
                       priority="latency"))
  sched.plan_step()
  assert {s.req.uid for s in sched.active.values()} == {"lat0"}
  assert sched.proactive_preemptions == 0
  flat = FCFSScheduler(num_slots=1, prefill_chunk=4, max_seq_len=32,
                       clock=clock)
  flat.submit(Request(uid="t", prompt=a, max_new_tokens=8))
  flat.plan_step()
  flat.commit(np.asarray([[1]], np.int32))
  flat.submit(Request(uid="lat", prompt=b, max_new_tokens=4,
                      priority="latency"))
  flat.plan_step()
  assert {s.req.uid for s in flat.active.values()} == {"t"}


# --------------------------------------------------------- fleet rollup


def test_fleet_summary_merges_raw_samples_and_counters():
  clock = FakeClock()
  s1, s2 = ServingStats(clock=clock), ServingStats(clock=clock)
  for stats, uid, ttft in ((s1, "a", 1.0), (s2, "b", 3.0)):
    stats.note_submitted(uid)
    clock.advance(ttft)
    stats.note_first_token(uid)
    clock.advance(1.0)
    stats.note_finished(uid, 11, "length")
    stats.note_step(active_slots=1, num_slots=2, prefill_tokens=0,
                    decode_tokens=10, step_time_s=1.0)
  s1.note_shed("x")
  out = fleet_summary([s1, s2], {"failovers": 1, "router_shed": 2})
  assert out["replicas"] == 2.0
  assert out["finished_requests"] == 2.0
  assert out["generated_tokens"] == 22.0
  # Rates SUM across concurrently-serving replicas.
  assert out["tokens_per_s"] == pytest.approx(11.0 + 11.0)
  # Percentiles re-rank over merged raw samples: p50 of {1, 3} by
  # nearest-rank is one of the samples, never their mean.
  assert out["ttft_p50_s"] in (1.0, 3.0)
  assert out["ttft_p99_s"] == 3.0
  assert out["shed"] == 1.0 and out["router_shed"] == 2.0
  assert out["failovers"] == 1.0
  assert out["slot_occupancy_mean"] == pytest.approx(0.5)


def test_fleet_rollup_report_reads_registry_jsonl(tmp_path):
  """The report CLI's --metrics path: a Router-published serving/fleet
  record round-trips through the registry's JSONL sink into the
  formatted block (satellite: fleet rollup shown by
  observability.report)."""
  path = str(tmp_path / "metrics.jsonl")
  writer = MetricsWriter(path)
  registry = MetricRegistry(writer)
  registry.publish(3, {"tokens_per_s": 12.5, "replicas": 2.0,
                       "replicas_healthy": 1.0, "replicas_down": 1.0,
                       "failovers": 1.0, "shed": 0.0}, "serving/fleet")
  registry.publish(4, {"loss": 0.5}, "train")    # non-fleet line after
  writer.close()
  fleet = fleet_rollup(path)
  assert fleet is not None and fleet["step"] == 3
  assert fleet["tokens_per_s"] == 12.5
  text = format_fleet(fleet)
  assert "2 replica(s)" in text and "failovers 1" in text
  assert fleet_rollup(str(tmp_path / "missing.jsonl")) is None


# ------------------------------------------------ routing policy units


def test_router_dispatch_least_loaded_and_affinity():
  router, reps, _ = _fake_router([2, 0])
  p1, p2 = _prompts((6, 6), seed=5)
  assert router.submit(Request(uid="a", prompt=p1, max_new_tokens=2))
  assert reps[1].submitted == ["a"]              # least-loaded wins
  # Same prefix routes back to replica 1 (affinity) even once loads
  # tie; a DIFFERENT prefix falls back to least-loaded.
  reps[0]._load = 0
  idx, reason = router._choose(np.asarray(p1, np.int32))
  assert (idx, reason) == (1, "affinity")
  idx, reason = router._choose(np.asarray(p2, np.int32))
  assert (idx, reason) == (0, "least_loaded")
  # A saturated affinity target is only a hint: fall back.
  reps[1]._load = reps[1].num_slots
  idx, reason = router._choose(np.asarray(p1, np.int32))
  assert (idx, reason) == (0, "least_loaded")


def test_router_dispatch_degrades_to_round_robin_on_stale_signals():
  router, reps, clock = _fake_router([5, 0], heartbeat_s=1.0,
                                     suspect_after=60.0,
                                     down_after=120.0)
  for r in reps:
    r.has_work = True      # only a replica OWING work can go stale
  clock.advance(5.0)       # no beats for 5s: stale but not yet suspect
  choices = {router._choose(np.asarray([1, 2], np.int32))
             for _ in range(4)}
  assert all(reason == "round_robin" for _, reason in choices)
  assert {idx for idx, _ in choices} == {0, 1}   # load 5 ranked no more


def test_idle_fleet_never_ages_out_between_bursts():
  """Regression: heartbeats only happen in step(), so a healthy fleet
  idle past suspect_after/down_after must NOT be aged suspect/down at
  the next submit — an idle replica owes no beats, and shedding the
  first request after every lull would be self-inflicted unavailability.
  """
  router, reps, clock = _fake_router([0, 0])
  clock.advance(10_000.0)                  # far past down_after
  (p,) = _prompts((4,), seed=20)
  assert router.submit(Request(uid="late", prompt=p, max_new_tokens=2))
  assert router.states() == ["healthy", "healthy"]
  assert router.router_shed == 0


def test_stale_loaded_replica_reaped_at_submit():
  """Regression: a replica HOLDING work whose heartbeat ages past
  down_after without ever raising must be failed over at dispatch time
  (the passive death path) — not skipped forever by the step loop's
  down-guard, stranding its queue."""
  router, reps, clock = _fake_router([1, 0])
  reps[0].has_work = True
  reps[0].snaps = [{"request": {"uid": "stranded", "prompt": [1, 2]},
                    "generated": [], "requeues": 0,
                    "first_token_emitted": False, "submitted_at": 0.0}]
  clock.advance(1000.0)                    # past down_after, no beats
  (p,) = _prompts((4,), seed=21)
  assert router.submit(Request(uid="new", prompt=p, max_new_tokens=2))
  assert router.state(0) == "down"
  assert router.failovers == 1 and router.migrated_requests == 1
  assert reps[1].restored == ["stranded"]
  assert router.placement["stranded"] == 1
  assert router.placement["new"] == 1      # routed around the corpse


def test_cancel_reaches_parked_requests():
  """Regression: a parked request (total outage) must be cancellable —
  otherwise it silently resurrects on the next rejoin after the client
  abandoned it."""
  router, reps, _ = _fake_router([0])
  router._parked.append({"request": {"uid": "p1", "prompt": [1, 2, 3]},
                         "generated": [7], "requeues": 0,
                         "first_token_emitted": True,
                         "submitted_at": 0.0})
  assert router.cancel("p1") is True
  assert not router._parked
  fin = router.finished["p1"]
  assert fin.finish_reason == "cancelled" and fin.new_tokens == 1
  np.testing.assert_array_equal(fin.tokens, [1, 2, 3, 7])
  assert router.cancel("ghost") is False


def test_router_sheds_when_no_replica_routable():
  router, reps, _ = _fake_router([0, 0])
  router.health[0].mark_down("chaos")
  router.health[1].drain()
  (p,) = _prompts((4,), seed=6)
  assert router.submit(Request(uid="x", prompt=p, max_new_tokens=2)) \
      is False
  assert router.finished["x"].finish_reason == "shed"
  assert router.router_shed == 1
  assert router.fleet_summary()["router_shed"] == 1.0
  # Replica-level shed is mirrored, not recounted.
  router.health[1].rejoin()
  reps[1].accept = False
  assert not router.submit(Request(uid="y", prompt=p, max_new_tokens=2))
  assert router.finished["y"].finish_reason == "shed"
  assert router.router_shed == 1


# ----------------------------------------------- engine: quick matrix


@pytest.mark.quick
def test_single_replica_router_fault_free_bit_exact_zero_recompile():
  """Quick acceptance (ISSUE 8): a Router with N=1 and no faults is a
  pure pass-through — token streams bit-identical to the bare engine
  (and the generate() oracle) with the one fused step still compiled
  ONCE (the control plane adds no device work)."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3, 9, 2))
  max_new = (6, 7, 4, 5)

  def drive(make):
    eng_like = make()
    for i in range(2):
      assert eng_like.submit(Request(uid=i, prompt=prompts[i],
                                     max_new_tokens=max_new[i]))
    out = {}
    for _ in range(2):
      for fin in eng_like.step():
        out[fin.uid] = fin.tokens
    for i in range(2, 4):                        # staggered second wave
      assert eng_like.submit(Request(uid=i, prompt=prompts[i],
                                     max_new_tokens=max_new[i]))
    out.update(eng_like.run())
    return out

  base = drive(lambda: ContinuousBatchingEngine(
      model, params, num_slots=2, prefill_chunk=4))
  router = Router(model, params, num_replicas=1, num_slots=2,
                  prefill_chunk=4)
  routed = drive(lambda: router)
  assert router.replicas[0].engine._step_fn._cache_size() == 1
  assert router.failovers == 0 and router.states() == ["healthy"]
  assert sorted(base) == sorted(routed) == list(range(4))
  for i in range(4):
    np.testing.assert_array_equal(routed[i], base[i], err_msg=f"req {i}")
    np.testing.assert_array_equal(
        routed[i], _oracle(model, params, prompts[i], max_new[i]))
    assert router.finished[i].finish_reason == "length"


@pytest.mark.quick
def test_replica_kill_mid_decode_bit_exact_failover():
  """The headline (`make chaos-router` acceptance): kill one of two
  replicas mid-decode — its queued + in-flight requests fail over to
  the survivor and EVERY request finishes with the exact oracle stream;
  the survivor's fused step stays compiled once (failover is a prefix
  replay, not a new shape)."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3, 9, 2), seed=8)
  router = Router(model, params, num_replicas=2, num_slots=2,
                  prefill_chunk=4)
  # Let replica 0 decode a few tokens before dying, so the failover
  # carries COMMITTED MID-FLIGHT state, not just queued prompts.
  killer = chaos.ReplicaKiller(router.replicas[0].engine,
                               kill_calls=(3,))
  for i, p in enumerate(prompts):
    assert router.submit(Request(uid=i, prompt=p, max_new_tokens=6))
  assert {router.placement[i] for i in range(4)} == {0, 1}
  out = router.run()
  assert killer.kills == 1
  assert router.failovers == 1 and router.migrated_requests == 2
  assert router.states() == ["down", "healthy"]
  assert router.replicas[1].engine._step_fn._cache_size() == 1, \
      "failover must not recompile the survivor's fused step"
  assert len(router.finished) == 4
  for i, p in enumerate(prompts):
    assert router.finished[i].finish_reason == "length"
    np.testing.assert_array_equal(out[i], _oracle(model, params, p, 6),
                                  err_msg=f"req {i}")
  fleet = router.fleet_summary()
  assert fleet["finished_requests"] == 4.0      # nothing double-counted
  assert fleet["failovers"] == 1.0


# --------------------------------------------------- chaos: slow suite


@pytest.mark.slow
def test_graceful_drain_completes_then_rejoin_resumes():
  """Drain with headroom: the draining replica finishes its own work
  within the timeout (nothing migrates), stays unroutable until rejoin,
  and rejoin resumes admission warm — zero recompiles across the whole
  restart cycle."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3, 4, 6), seed=9)
  router = Router(model, params, num_replicas=2, num_slots=2,
                  prefill_chunk=4)
  for i in range(3):
    router.submit(Request(uid=i, prompt=prompts[i], max_new_tokens=6))
  router.step()
  drained = router.placement[0]
  router.drain(drained)                    # default timeout: plenty
  out = router.run()
  assert router.migrated_requests == 0     # it finished its own load
  assert router.state(drained) == "draining"
  assert not router.replicas[drained].has_work
  assert router.rejoin(drained)
  assert router.state(drained) == "healthy"
  # Rejoined replica takes traffic again, warm (compile count still 1).
  other = 1 - drained
  router.health[other].drain()
  router.submit(Request(uid=3, prompt=prompts[3], max_new_tokens=6))
  assert router.placement[3] == drained
  out.update(router.run())
  assert router.replicas[drained].engine._step_fn._cache_size() == 1
  for i in range(4):
    np.testing.assert_array_equal(
        out[i], _oracle(model, params, prompts[i], 6), err_msg=f"req {i}")


@pytest.mark.slow
def test_drain_timeout_migrates_leftovers_bit_exact():
  """Drain with NO headroom (timeout 0): the replica's queued and
  in-flight requests migrate to the survivor immediately and still
  finish bit-exactly — the rolling-restart worst case."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3, 9, 2), seed=10)
  router = Router(model, params, num_replicas=2, num_slots=2,
                  prefill_chunk=4)
  for i, p in enumerate(prompts):
    router.submit(Request(uid=i, prompt=p, max_new_tokens=6))
  router.step()                            # both replicas mid-flight
  drained = 0
  router.drain(drained, timeout_s=0.0)
  out = router.run()
  assert router.migrated_requests >= 1
  assert len(out) == 4 and len(router.finished) == 4
  for i, p in enumerate(prompts):
    assert router.finished[i].finish_reason == "length"
    np.testing.assert_array_equal(out[i], _oracle(model, params, p, 6),
                                  err_msg=f"req {i}")
  assert router.replicas[1].engine._step_fn._cache_size() == 1
  # The degradation/shed ledger stayed consistent: nothing shed, every
  # submit resolved exactly once.
  assert router.fleet_summary()["shed"] == 0.0
  assert router.fleet_summary()["finished_requests"] == 4.0


@pytest.mark.slow
def test_replica_hang_marks_suspect_outputs_exact():
  """A hung replica step trips ITS StepWatchdog (the async detector);
  the timeout count rides the next heartbeat and the health machine
  marks the replica suspect, recovering on the next clean beat — a
  latency fault only, streams stay bit-exact and nothing migrates."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3), seed=11)
  config = epl.Config({"serving": {"resilience": {
      "enabled": True, "step_timeout_s": 0.05}}})
  router = Router(model, params, num_replicas=2, num_slots=2,
                  prefill_chunk=4, config=config)
  try:
    inj = chaos.ReplicaHang(router.replicas[0].engine, hang_calls=(1,),
                            hang_s=0.4)
    transitions = []
    router.health[0].on_transition = \
        lambda old, new, reason: transitions.append((old, new))
    for i, p in enumerate(prompts):
      router.submit(Request(uid=i, prompt=p, max_new_tokens=6))
    out = router.run()
  finally:
    router.close()
  assert inj.hangs == 1
  assert router.replicas[0].stats.watchdog_timeouts >= 1
  assert ("healthy", "suspect") in transitions
  assert ("suspect", "healthy") in transitions  # clean beat recovered it
  assert router.failovers == 0 and router.migrated_requests == 0
  for i, p in enumerate(prompts):
    np.testing.assert_array_equal(out[i], _oracle(model, params, p, 6),
                                  err_msg=f"req {i}")


@pytest.mark.slow
def test_flapping_replica_breaker_backoff():
  """A replica that keeps dying and rejoining: every trip doubles the
  breaker hold-out, so the flapper converges to parked while the stable
  survivor serves everything bit-exactly."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3, 4, 6, 2, 7), seed=12)
  clock = FakeClock()
  router = Router(model, params, num_replicas=2, num_slots=2,
                  prefill_chunk=4, clock=clock)
  chaos.FlappingHealth(router.replicas[0].engine, fail_every=2)
  h = router.health[0]
  seen_cooldowns = []
  next_uid = 0
  for _ in range(400):
    if (h.state == "healthy" and next_uid < len(prompts)
        and not router.replicas[0].has_work):
      # Keep handing the flapper work each time it claims recovery —
      # the flap only reproduces under load.
      router.replicas[0].submit(Request(uid=next_uid,
                                        prompt=prompts[next_uid],
                                        max_new_tokens=6))
      next_uid += 1
    if h.state == "down":
      if not seen_cooldowns or seen_cooldowns[-1] != h.cooldown_s():
        seen_cooldowns.append(h.cooldown_s())
      clock.advance(h.cooldown_s() + 1.0)   # let the breaker probe
    router.step()
    if next_uid >= len(prompts) and not router.has_work:
      break
  assert not router.has_work
  assert h.trips >= 2, "flapper must trip the breaker repeatedly"
  # Exponential hold-out: each successive cooldown doubled.
  assert seen_cooldowns == sorted(seen_cooldowns)
  assert len(seen_cooldowns) >= 2
  assert seen_cooldowns[1] == 2 * seen_cooldowns[0]
  assert router.probes >= 1
  for i, p in enumerate(prompts):
    assert router.finished[i].finish_reason == "length"
    np.testing.assert_array_equal(
        router.finished[i].tokens, _oracle(model, params, p, 6),
        err_msg=f"req {i}")


@pytest.mark.slow
def test_total_outage_parks_requests_until_rejoin():
  """Killing the ONLY replica parks its requests (an outage delays,
  never loses); a forced rejoin flushes the parked backlog and every
  request still finishes bit-exactly."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3), seed=13)
  router = Router(model, params, num_replicas=1, num_slots=2,
                  prefill_chunk=4)
  chaos.ReplicaKiller(router.replicas[0].engine, kill_calls=(2,))
  for i, p in enumerate(prompts):
    router.submit(Request(uid=i, prompt=p, max_new_tokens=6))
  out = router.run()                       # returns: everything parked
  assert not out and router.states() == ["down"]
  assert router.router_counters()["parked"] == 2.0
  assert len(router.finished) == 0, "parked requests are NOT resolved"
  assert router.rejoin(0, force=True)
  out = router.run()
  assert router.router_counters()["parked"] == 0.0
  for i, p in enumerate(prompts):
    assert router.finished[i].finish_reason == "length"
    np.testing.assert_array_equal(out[i], _oracle(model, params, p, 6),
                                  err_msg=f"req {i}")
  assert router.replicas[0].engine._step_fn._cache_size() == 1


@pytest.mark.slow
def test_proactive_preemption_on_paged_engine_bit_exact():
  """Device-level proactive preemption: on the paged engine a
  latency-class arrival evicts a running throughput slot at admission;
  BOTH requests still finish bit-exact vs the oracle (the victim
  replays its committed prefix) and the eviction is counted under
  serving/proactive_preemptions."""
  epl.init()
  model, params = _model_and_params()
  lat_p, t0_p, t1_p = _prompts((4, 5, 3), seed=14)
  eng = ContinuousBatchingEngine(
      model, params, num_slots=2, prefill_chunk=4, paged=True,
      block_size=4, token_budget=12, resilience=True)
  eng.submit(Request(uid="t0", prompt=t0_p, max_new_tokens=8))
  eng.submit(Request(uid="t1", prompt=t1_p, max_new_tokens=8))
  for _ in range(4):
    eng.step()                             # both throughput mid-decode
  eng.submit(Request(uid="lat", prompt=lat_p, max_new_tokens=4,
                     priority="latency"))
  out = eng.run()
  assert eng.scheduler.proactive_preemptions == 1
  assert eng.stats.proactive_preemptions == 1
  assert eng._step_fn._cache_size() == 1
  for uid, p, mx in (("t0", t0_p, 8), ("t1", t1_p, 8), ("lat", lat_p, 4)):
    assert eng.finished[uid].finish_reason == "length"
    np.testing.assert_array_equal(
        out[uid], _oracle(model, params, p, mx), err_msg=uid)
