"""Runtime feature tests: gradient accumulation, AMP/loss scale, grouped
apply, remat helpers, offload (reference analogs: tests/ga_test.py,
tests/amp_*.py, tests/gradient_checkpoint_test.py, tests/offload_test.py)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu import ops
from easyparallellibrary_tpu.parallel import (
    create_sharded_train_state, parallelize)
from easyparallellibrary_tpu.runtime import amp as amp_lib
from easyparallellibrary_tpu.runtime import gc as gc_lib
from easyparallellibrary_tpu.runtime.gradient_accumulation import (
    accumulate_gradients)
from easyparallellibrary_tpu.runtime.offload import offload_to_host
from easyparallellibrary_tpu.runtime.optimizer_helper import apply_grad_group
from easyparallellibrary_tpu.runtime.trainer import (
    build_train_step, create_train_state)


class Net(nn.Module):
  @nn.compact
  def __call__(self, x):
    return ops.Dense(1, parallel="none")(jnp.tanh(
        ops.Dense(16, parallel="none")(x)))


def _setup(config=None):
  env = epl.init(config)
  mesh = epl.current_plan().build_mesh()
  model = Net()
  r = np.random.RandomState(0)
  x = jnp.asarray(r.randn(16, 8), jnp.float32)
  y = jnp.asarray(r.randn(16, 1), jnp.float32)

  def loss_fn(params, batch, rng):
    pred = model.apply({"params": params}, batch["x"])
    return jnp.mean((pred - batch["y"]) ** 2), {}

  params = model.init(jax.random.PRNGKey(0), x)["params"]
  return env, mesh, model, loss_fn, params, {"x": x, "y": y}


def test_gradient_accumulation_matches_full_batch():
  env, mesh, model, loss_fn, params, batch = _setup()
  grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
  (l_full, _), g_full = grad_fn(params, batch, None)
  (l_ga, _), g_ga = accumulate_gradients(grad_fn, 4)(params, batch, None)
  np.testing.assert_allclose(float(l_full), float(l_ga), rtol=1e-6)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7),
      g_full, g_ga)


def test_ga_aux_includes_every_micro_batch():
  """The aux average must cover all micro-batches, including the first
  (round-1 bug: first slice's aux was dropped, scaling aux by (n-1)/n)."""
  env, mesh, model, loss_fn, params, batch = _setup()

  def loss_with_aux(params, b, rng):
    loss, _ = loss_fn(params, b, rng)
    # Aux that depends on the data: mean of the slice's inputs.
    return loss, {"x_mean": jnp.mean(b["x"])}

  grad_fn = jax.value_and_grad(loss_with_aux, has_aux=True)
  (_, aux_full), _ = grad_fn(params, batch, None)
  (_, aux_ga), _ = accumulate_gradients(grad_fn, 4)(params, batch, None)
  # Mean over 4 slice-means == full mean only if all 4 slices counted.
  np.testing.assert_allclose(
      float(aux_full["x_mean"]), float(aux_ga["x_mean"]), rtol=1e-6)


def test_ga_rng_differs_per_micro_batch():
  """Dropout masks must differ across micro-batches (rng folded per slice)."""
  env = epl.init()

  def noise_fn(params, b, rng):
    # "Gradient" is pure rng noise: identical rngs would make the
    # accumulated average equal each slice's noise exactly.
    noise = jax.random.normal(rng, (4,))
    return jnp.float32(0), {"noise": noise}

  def grad_fn(params, b, rng):
    _, aux = noise_fn(params, b, rng)
    return (jnp.float32(0), aux), {"w": jnp.zeros(())}

  batch = {"x": jnp.zeros((8, 2))}
  rng = jax.random.PRNGKey(42)
  (_, aux), _ = accumulate_gradients(grad_fn, 4)(params=None, batch=batch,
                                                 rng=rng)
  # Each micro-batch i must see fold_in(rng, i); the returned aux is the
  # average over all four distinct noises.
  expected = np.mean(
      [np.asarray(jax.random.normal(jax.random.fold_in(rng, i), (4,)))
       for i in range(4)], axis=0)
  np.testing.assert_allclose(np.asarray(aux["noise"]), expected, rtol=1e-5)
  single = np.asarray(jax.random.normal(jax.random.fold_in(rng, 0), (4,)))
  assert not np.allclose(np.asarray(aux["noise"]), single)


def test_grouped_apply_dce_trims_each_call():
  """The grouped-apply memory claim is real only if XLA DCE trims every
  per-group tx.update to its group's leaves — verified here by compiled
  FLOPs: grouped must cost the same as one full update, not N of them
  (VERDICT round-1 weak item 5)."""
  epl.init()
  r = np.random.RandomState(0)
  params = {f"w{i}": jnp.asarray(r.randn(256, 256), jnp.float32)
            for i in range(8)}
  grads = {f"w{i}": jnp.asarray(r.randn(256, 256), jnp.float32)
           for i in range(8)}
  tx = optax.adam(1e-3)
  opt = tx.init(params)

  def flops(ng):
    f = jax.jit(lambda p, g, o: apply_grad_group(tx, p, g, o, ng))
    cost = f.lower(params, grads, opt).compile().cost_analysis()
    return float(cost.get("flops", 0.0))

  base = flops(1)
  assert flops(4) <= base * 1.05, (flops(4), base)
  assert flops(8) <= base * 1.05, (flops(8), base)

  # And the grouped result is bit-compatible with the ungrouped one.
  p1, s1 = jax.jit(lambda: apply_grad_group(tx, params, grads, opt, 1))()
  p8, s8 = jax.jit(lambda: apply_grad_group(tx, params, grads, opt, 8))()
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-9),
      p1, p8)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-9),
      s1, s8)


def test_grouped_apply_state_ownership_longest_suffix():
  """A top-level "kernel" must not steal ownership of a nested
  ".../layer/kernel" state leaf (suffix-collision regression)."""
  from easyparallellibrary_tpu.runtime.optimizer_helper import (
      _match_state_leaves_to_groups)
  params = {"kernel": jnp.zeros((4, 4)),
            "layer": {"kernel": jnp.ones((4, 4))}}
  tx = optax.adam(1e-3)
  opt = tx.init(params)
  # Two groups: leaf 0 = "kernel", leaf 1 = "layer/kernel".
  owners = _match_state_leaves_to_groups(params, opt, [[0], [1]])
  # Adam state: (count, mu{kernel, layer/kernel}, nu{...}), count=None.
  assert owners.count(None) == 1
  assert owners.count(0) == 2 and owners.count(1) == 2


def test_amp_o1_sets_model_compute_dtype():
  """amp.level="O1" switches a default-fp32 bundled model to bf16 compute
  without touching params (VERDICT round-1 item 8; reference effect:
  epl/runtime/amp/auto_mixed_precision.py:174-191)."""
  from easyparallellibrary_tpu.models import GPT, GPTConfig

  cfg = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                  d_ff=64, max_seq_len=16, dtype=jnp.float32)
  ids = jnp.zeros((2, 8), jnp.int32)

  epl.init()
  model = GPT(cfg)
  params = model.init(jax.random.PRNGKey(0), ids)["params"]
  out_off = jax.eval_shape(
      lambda p: model.apply({"params": p}, ids), params)
  assert out_off.dtype == jnp.float32

  epl.init(epl.Config({"amp.level": "O1"}))
  out_on = jax.eval_shape(
      lambda p: model.apply({"params": p}, ids), params)
  assert out_on.dtype == jnp.bfloat16
  # Params stay fp32 (O1: bf16 compute, fp32 master weights).
  kernel = params["wte"]["embedding"]
  kernel = kernel.value if hasattr(kernel, "value") else kernel
  assert kernel.dtype == jnp.float32


def test_amp_policy_wrap_apply_generic_module():
  """Policy.wrap_apply casts an arbitrary module to mixed precision."""
  epl.init()
  dense = nn.Dense(8)
  x = jnp.ones((4, 4), jnp.float32)
  params = dense.init(jax.random.PRNGKey(0), x)["params"]

  plain = dense.apply({"params": params}, x)
  assert plain.dtype == jnp.float32

  policy = amp_lib.Policy()
  mixed_fn = policy.wrap_apply(
      lambda p, v: dense.apply({"params": p}, v))
  intermediate = jax.eval_shape(
      lambda p, v: dense.apply({"params": policy.cast_to_compute(p)},
                               policy.cast_to_compute(v)), params, x)
  assert intermediate.dtype == jnp.bfloat16     # compute ran in bf16
  out = mixed_fn(params, x)
  assert out.dtype == jnp.float32               # output cast back
  np.testing.assert_allclose(np.asarray(out), np.asarray(plain),
                             rtol=2e-2, atol=2e-2)


def test_amp_policy_from_config():
  assert amp_lib.policy_from_config(epl.Config({})) is None
  pol = amp_lib.policy_from_config(epl.Config({"amp.level": "O1"}))
  assert pol is not None and pol.compute_dtype == jnp.bfloat16
  pol16 = amp_lib.policy_from_config(
      epl.Config({"amp.level": "O1", "amp.compute_dtype": "fp16"}))
  assert pol16.compute_dtype == jnp.float16


def test_ga_config_driven_training_matches():
  def run(cfg_dict):
    env, mesh, model, loss_fn, params, batch = _setup(epl.Config(cfg_dict))
    tx = optax.sgd(0.1)
    state = create_train_state(model.apply, params, tx)
    step = build_train_step(loss_fn)
    losses = []
    for _ in range(5):
      state, m = step(state, batch, None)
      losses.append(float(m["loss"]))
    return losses

  # GA over 4 micro-batches == full batch (loss values identical since
  # grads are averaged over the same samples).
  np.testing.assert_allclose(
      run({"pipeline.num_micro_batch": 4}), run({}), rtol=1e-5)


def test_dynamic_loss_scale_backoff_and_growth():
  scale = amp_lib.DynamicLossScale.create(initial_scale=1024.0,
                                          growth_interval=2)
  s1 = scale.update(jnp.bool_(False))       # overflow -> halve
  assert float(s1.scale) == 512.0
  s2 = s1.update(jnp.bool_(True))
  s3 = s2.update(jnp.bool_(True))           # 2 finite steps -> grow
  assert float(s3.scale) == 1024.0


def test_amp_fp16_training_skips_nonfinite_updates():
  cfg = epl.Config({"amp.level": "O1", "amp.loss_scale": "dynamic"})
  env, mesh, model, loss_fn, params, batch = _setup(cfg)

  calls = {"n": 0}

  def exploding_loss(params, batch, rng):
    loss, aux = loss_fn(params, batch, rng)
    # Inject an inf on the first call via where on a traced value is not
    # possible; instead scale loss hugely so fp16-style overflow appears
    # in grads only when loss_scale is enormous.
    return loss, aux

  tx = optax.sgd(0.1)
  state = create_train_state(model.apply, params, tx, config=cfg)
  assert hasattr(state, "loss_scale")
  step = build_train_step(loss_fn, config=cfg)
  p0 = jax.tree_util.tree_leaves(state.params)[0].copy()
  state, m = step(state, batch, None)
  assert "loss_scale" in m and "grads_finite" in m
  assert float(m["grads_finite"]) == 1.0
  # Params actually moved.
  p1 = jax.tree_util.tree_leaves(state.params)[0]
  assert float(jnp.max(jnp.abs(p1 - p0))) > 0


def test_loss_scale_skip_on_overflow():
  cfg = epl.Config({"amp.level": "O1", "amp.loss_scale": "dynamic"})
  env, mesh, model, _, params, batch = _setup(cfg)

  def inf_loss(params, batch, rng):
    leaf = jax.tree_util.tree_leaves(params)[0]
    return jnp.sum(leaf) * jnp.inf, {}

  # adamw: weight decay would perturb params even with zeroed grads, so
  # this also guards the true-no-op semantics of the skip.
  tx = optax.adamw(0.1, weight_decay=0.1)
  state = create_train_state(model.apply, params, tx, config=cfg)
  opt0 = jax.tree_util.tree_map(lambda x: x, state.opt_state)
  step = build_train_step(inf_loss, config=cfg)
  s0 = float(state.loss_scale.scale)
  state, m = step(state, batch, None)
  assert float(m["grads_finite"]) == 0.0
  assert float(state.loss_scale.scale) == s0 / 2  # backoff
  assert int(state.step) == 0                     # step not advanced
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b),
      state.params, params)  # update skipped entirely
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b),
      state.opt_state, opt0)  # optimizer moments untouched


def test_grouped_apply_matches_plain():
  env, mesh, model, loss_fn, params, batch = _setup()
  tx = optax.adam(1e-2)
  opt_state = tx.init(params)
  (_, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
      params, batch, None)

  import optax as ox
  updates, ref_state = tx.update(grads, opt_state, params)
  ref_params = ox.apply_updates(params, updates)
  for groups in (1, 2, 4):
    p, s = apply_grad_group(tx, params, grads, opt_state, groups)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6),
        p, ref_params)


def test_gc_collection_policy_grads_match():
  env, mesh, model, loss_fn, params, batch = _setup(
      epl.Config({"gradient_checkpoint.type": "collection",
                  "gradient_checkpoint.check_gradients": True}))

  def f(params):
    h = jnp.tanh(params["Dense_0"]["kernel"].value.sum())
    h = gc_lib.mark_checkpoint(h)
    return h * h

  g1 = gc_lib.gradients(f)(params)
  g2 = jax.grad(f)(params)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), g1, g2)


def test_offload_shardings_fallback_on_cpu():
  env, mesh, model, loss_fn, params, batch = _setup()
  tx = optax.adam(1e-2)

  from easyparallellibrary_tpu.parallel import TrainState

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, batch["x"])["params"],
                             tx=tx)

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  moved = offload_to_host(shardings)  # CPU backend: warns, no crash
  assert jax.tree_util.tree_structure(
      moved, is_leaf=lambda x: hasattr(x, "memory_kind")
  ) is not None


def test_auto_checkpoint_segments():
  segs = gc_lib.auto_checkpoint_segments([1.0] * 16)
  assert segs[0] == 0 and len(segs) == 4  # sqrt(16)
  # Memory-balanced: a huge block gets its own segment boundary.
  segs2 = gc_lib.auto_checkpoint_segments([1, 1, 100, 1, 1, 1], 2)
  assert 2 in segs2 or segs2 == [0, 3]


def test_mutable_train_step_batchnorm():
  from easyparallellibrary_tpu.parallel import (
      MutableTrainState, make_mutable_train_step)

  class BNNet(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
      x = ops.Dense(8, parallel="none")(x)
      x = nn.BatchNorm(use_running_average=not train)(x)
      return ops.Dense(1, parallel="none")(x)

  env = epl.init()
  mesh = epl.current_plan().build_mesh()
  model = BNNet()
  x = jnp.asarray(np.random.RandomState(0).randn(16, 4), jnp.float32)
  y = jnp.asarray(np.random.RandomState(1).randn(16, 1), jnp.float32)
  variables = model.init(jax.random.PRNGKey(0), x)

  def init_fn(rng):
    v = model.init(rng, x)
    return MutableTrainState.create(
        apply_fn=model.apply, params=v["params"], tx=optax.adam(1e-2),
        model_state={"batch_stats": v["batch_stats"]})

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))

  def loss_fn(params, model_state, batch, rng):
    out, new_ms = model.apply({"params": params, **model_state},
                              batch["x"], train=True,
                              mutable=["batch_stats"])
    return jnp.mean((out - batch["y"]) ** 2), ({}, new_ms)

  step = parallelize(make_mutable_train_step(loss_fn), mesh, shardings)
  stats0 = jax.tree_util.tree_leaves(state.model_state)[0].copy()
  losses = []
  for _ in range(8):
    state, m = step(state, {"x": x, "y": y}, jax.random.PRNGKey(2))
    losses.append(float(m["loss"]))
  assert losses[-1] < losses[0]
  stats1 = jax.tree_util.tree_leaves(state.model_state)[0]
  assert float(jnp.max(jnp.abs(stats1 - stats0))) > 0  # stats updated


def test_plan_format():
  epl.init(epl.Config({"zero.level": "v0"}))
  with epl.replicate(1):
    pass
  with epl.split(2):
    pass
  plan = epl.current_plan()
  plan.build_mesh()
  text = plan.format()
  assert "taskgraph[0]" in text and "kind=replicate" in text
  assert "kind=split" in text
  assert "mesh:" in text and "zero=v0" in text


def test_config_driven_zero_and_offload_defaults():
  """create_sharded_train_state picks up zero.level/offload.level from
  the active Config without explicit arguments."""
  import jax
  from jax.sharding import PartitionSpec as P
  env, mesh, model, loss_fn, params, batch = _setup(
      epl.Config({"zero.level": "v0"}))
  from easyparallellibrary_tpu.parallel import TrainState

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, batch["x"])["params"],
                             tx=optax.adam(1e-2))

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))  # no zero_level arg
  specs = [s.spec for s in jax.tree_util.tree_leaves(
      shardings.opt_state, is_leaf=lambda x: hasattr(x, "spec"))]
  assert any("data" in str(s) for s in specs)


def test_amp_policy_cast():
  from easyparallellibrary_tpu.runtime.amp import Policy
  p = Policy()
  tree = {"w": jnp.ones((2, 2), jnp.float32), "i": jnp.ones((2,), jnp.int32)}
  cast = p.cast_to_compute(tree)
  assert cast["w"].dtype == jnp.bfloat16
  assert cast["i"].dtype == jnp.int32  # non-float leaves untouched


def test_profile_step_static_report():
  from easyparallellibrary_tpu.profiler.profiler import profile_step

  def step(x):
    return (x @ x).sum()

  rep = profile_step(step, jnp.ones((64, 64)), tokens_per_step=128,
                     num_stages=4, num_micro_batch=4)
  assert rep.get("cost_flops", 0) > 0
  assert rep["pipeline_bubble"] == 3 / 7
  assert rep["tokens_per_step"] == 128.0
