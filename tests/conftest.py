"""Test harness: 8 virtual CPU devices.

Mirrors the reference's test strategy (SURVEY §4): the reference fakes 8
GPUs by monkey-patching `Cluster.available_gpus`
(/root/reference/tests/scheduler_test.py:37-48); here we ask XLA for 8
host-platform devices so sharding/collective logic runs for real, just on
CPU.  Must run before jax initializes its backends.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
  os.environ["XLA_FLAGS"] = (
      _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The image's sitecustomize imports jax at interpreter start with
# JAX_PLATFORMS already latched to the TPU plugin, so the env var alone is
# too late — override through the config (backends are not yet initialized
# at collection time).
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
  config.addinivalue_line(
      "markers", "slow: heavyweight tests excluded from the tier-1 run "
      "(`-m 'not slow'`)")
  config.addinivalue_line(
      "markers", "quick: one exactness test per composition "
      "(DP/TP/PP/SP/MoE/ZeRO/overlap) — `pytest -m quick` re-runs the "
      "whole matrix in <5 min on one core")


@pytest.fixture(autouse=True)
def _reset_epl_env():
  """Each test gets a fresh Env (the reference resets Env in epl.init)."""
  yield
  from easyparallellibrary_tpu.env import Env
  Env.get().reset()
