"""Sequence parallelism on the shard_map pipeline engines (VERDICT r4
item 2 — the last hole in the flagship engine's composition matrix).

The engines go manual over the seq axis and run stage compute
branch-uniformly (pipeline_smap.uniform_stage_compute), so ring
ppermutes / Ulysses all-to-alls execute unconditionally every tick —
XLA's collective-permute and all-to-all get a single whole-mesh channel
(only all-reduce has per-replica-group rendezvous), so any gated
execution deadlocks.  Numerics must match the sequential ground truth
exactly, including the seq-axis grad pmean (grad_mean_axes) and the
emit CE's local-token-mean -> pmean(seq) contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# Heavyweight engine-composition compiles (~8 min of XLA time): excluded
# from the tier-1 window, still run by `pytest tests/test_smap_sequence.py`.
pytestmark = pytest.mark.slow

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.models.gpt import (
    gpt_loss, make_gpt_smap_grad_fn)


def _check_matches_sequential(mesh_kw, cfg_kw, config_kw=None,
                              schedule="1f1b", rtol=5e-3):
  env = epl.init(epl.Config(dict({"sequence.ring_impl": "dense",
                                  "sequence.ulysses_impl": "einsum"},
                                 **(config_kw or {}))))
  mesh = env.cluster.build_mesh(**mesh_kw)
  base = dict(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
              d_ff=64, max_seq_len=16, dtype=jnp.float32,
              seq_parallel=True, pipeline_stages=2, num_micro_batch=2)
  base.update(cfg_kw)
  pp = GPT(GPTConfig(**base))
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 17)),
                    jnp.int32)
  params = pp.init(jax.random.PRNGKey(0), ids[:, :-1])["params"]
  seqm = GPT(GPTConfig(**base, pipeline_debug_sequential=True))

  grad_smap = make_gpt_smap_grad_fn(pp, mesh, schedule=schedule)
  (l1, _), g1 = jax.jit(lambda p: grad_smap(p, {"ids": ids}, None))(params)
  l2, g2 = jax.jit(jax.value_and_grad(
      lambda p: gpt_loss(seqm, p, {"ids": ids})[0]))(params)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=rtol, atol=1e-5),
      g1, g2)
  return float(l1)


def test_smap_ring_matches_sequential():
  """The headline composition: smap-1F1B x ring on a stage2 x data2 x
  seq2 mesh (pp x dp x sp in one engine)."""
  _check_matches_sequential(dict(stage=2, seq=2), {"attn_impl": "ring"})


def test_smap_gpipe_ring_matches_sequential():
  _check_matches_sequential(dict(stage=2, seq=2), {"attn_impl": "ring"},
                            schedule="gpipe")


def test_smap_interleaved_ring_matches_sequential():
  """Megatron-interleaved K=2 x ring: the newest schedule composes with
  sequence parallelism too."""
  _check_matches_sequential(dict(stage=2, seq=2),
                            {"attn_impl": "ring",
                             "pipeline_interleave": 2})


def test_smap_ring_tp_hybrid_matches_sequential():
  """pp2 x sp2 x tp2 — pipeline, sequence AND tensor parallelism in the
  one engine (model axis stays GSPMD-auto; ring rides the seq-manual
  region)."""
  _check_matches_sequential(dict(stage=2, seq=2, model=2),
                            {"attn_impl": "ring",
                             "tensor_parallel": True})


def test_smap_ring_zigzag_matches_sequential():
  """The zigzag causal layout's entry/exit ppermutes also run inside
  the engine region."""
  _check_matches_sequential(dict(stage=2, seq=2), {"attn_impl": "ring"},
                            {"sequence.ring_layout": "zigzag"})


def test_smap_ring_uneven_stages_matches_sequential():
  """5 layers over 2 stages: the masked slot stays branch-uniform
  (select) under seq-manual so the ring's permutes never gate."""
  _check_matches_sequential(dict(stage=2, seq=2),
                            {"attn_impl": "ring", "num_layers": 5})


def test_smap_ulysses_matches_sequential():
  _check_matches_sequential(dict(stage=2, seq=2),
                            {"attn_impl": "ulysses"})


def test_smap_ring_config_dispatch_trains():
  """pipeline.engine='smap' + attn_impl='ring' through
  make_gpt_train_step: the config-only path trains and the loss
  decreases."""
  import optax
  from easyparallellibrary_tpu.models.gpt import make_gpt_train_step
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state, parallelize)

  env = epl.init(epl.Config({"pipeline.engine": "smap",
                             "sequence.parallelism": "ring",
                             "sequence.axis_size": 2,
                             "sequence.ring_impl": "dense"}))
  cfg = GPTConfig(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
                  d_ff=64, max_seq_len=16, dtype=jnp.float32,
                  seq_parallel=True, attn_impl="ring",
                  pipeline_stages=2, num_micro_batch=2)
  with epl.replicate(1):
    model = GPT(cfg)
  mesh = env.cluster.build_mesh(stage=2, seq=2)
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 17)),
                    jnp.int32)

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, ids[:, :-1])["params"],
                             tx=optax.adam(1e-2))

  state, shardings = create_sharded_train_state(init_fn, mesh,
                                                jax.random.PRNGKey(0))
  step = parallelize(make_gpt_train_step(model), mesh, shardings)
  losses = []
  for i in range(4):
    state, m = step(state, {"ids": ids}, jax.random.PRNGKey(i))
    losses.append(float(m["loss"]))
  assert all(np.isfinite(l) for l in losses)
  assert losses[-1] < losses[0]


def test_smap_ring_token_divisibility_raises():
  env = epl.init(epl.Config({"sequence.parallelism": "ring",
                             "sequence.axis_size": 2,
                             "sequence.ring_impl": "dense"}))
  mesh = env.cluster.build_mesh(stage=2, seq=2)
  cfg = GPTConfig(vocab_size=64, num_layers=4, num_heads=2, d_model=16,
                  d_ff=32, max_seq_len=16, dtype=jnp.float32,
                  seq_parallel=True, attn_impl="ring",
                  pipeline_stages=2, num_micro_batch=2)
  grad_fn = make_gpt_smap_grad_fn(GPT(cfg), mesh)
  ids = jnp.zeros((4, 16), jnp.int32)  # 15 tokens % 2 != 0
  with pytest.raises(ValueError, match="seq shards"):
    grad_fn(None, {"ids": ids}, None)


def test_smap_ring_seq4_matches_sequential():
  """Deeper ring (stage2 x seq4, data=1): the wrap masking and the
  n-step rotation hold beyond the minimal two-device ring."""
  _check_matches_sequential(dict(stage=2, seq=4), {"attn_impl": "ring"})


def test_smap_ring_zero1_trains_and_scatters():
  """Composition stack: ring sequence parallelism x ZeRO-1 x smap — the
  seq-manual grad pmean composes with the owner reduce-scatter (seq
  pmean first, then scatter over data; pipeline_smap._reduce_grads)."""
  import optax
  from easyparallellibrary_tpu.models.gpt import make_gpt_train_step
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state, parallelize)

  def run(zero_level):
    conf = {"pipeline.engine": "smap",
            "sequence.parallelism": "ring",
            "sequence.axis_size": 2,
            "sequence.ring_impl": "dense"}
    if zero_level:
      conf["zero.level"] = zero_level
    env = epl.init(epl.Config(conf))
    cfg = GPTConfig(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
                    d_ff=64, max_seq_len=16, dtype=jnp.float32,
                    seq_parallel=True, attn_impl="ring",
                    pipeline_stages=2, num_micro_batch=2)
    with epl.replicate(1):
      model = GPT(cfg)
    mesh = env.cluster.build_mesh(stage=2, seq=2)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 17)),
                      jnp.int32)

    def init_fn(rng):
      return TrainState.create(
          apply_fn=model.apply,
          params=model.init(rng, ids[:, :-1])["params"],
          tx=optax.adam(1e-2))

    state, sh = create_sharded_train_state(
        init_fn, mesh, jax.random.PRNGKey(0), zero_level=zero_level)
    step = parallelize(make_gpt_train_step(model), mesh, sh)
    losses = []
    for i in range(3):
      state, m = step(state, {"ids": ids}, jax.random.PRNGKey(i))
      losses.append(float(m["loss"]))
    if zero_level:
      txt = step.jitted.lower(state, {"ids": ids},
                              jax.random.PRNGKey(9)).as_text()
      assert "reduce-scatter" in txt or "reduce_scatter" in txt
    return losses

  np.testing.assert_allclose(run("v1"), run(""), rtol=2e-5)


def test_smap_interleaved_ring_tp_stack_matches_sequential():
  """The deepest stack that fits 8 devices: pipeline x interleave-K2 x
  ring sequence parallelism x tensor parallelism, one engine program
  (the docs/tutorials.md §5 recipe)."""
  _check_matches_sequential(dict(stage=2, seq=2, model=2),
                            {"attn_impl": "ring",
                             "tensor_parallel": True,
                             "pipeline_interleave": 2})


def test_smap_ring_loss_scale_invariant():
  """AMP x sequence parallelism: the engine's backward seeded with a
  loss scale returns UNSCALED grads identical to the unscaled run —
  the seq-axis pmean calibration is linear in the seed."""
  env = epl.init(epl.Config({"sequence.ring_impl": "dense"}))
  mesh = env.cluster.build_mesh(stage=2, seq=2)
  cfg = GPTConfig(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
                  d_ff=64, max_seq_len=16, dtype=jnp.float32,
                  seq_parallel=True, attn_impl="ring",
                  pipeline_stages=2, num_micro_batch=2)
  pp = GPT(cfg)
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 17)),
                    jnp.int32)
  params = pp.init(jax.random.PRNGKey(0), ids[:, :-1])["params"]
  grad_fn = make_gpt_smap_grad_fn(pp, mesh)
  (l1, _), g1 = jax.jit(
      lambda p: grad_fn(p, {"ids": ids}, None))(params)
  (l2, _), g2 = jax.jit(
      lambda p: grad_fn(p, {"ids": ids}, None, 256.0))(params)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=1e-4, atol=1e-6),
      g1, g2)
