"""Full-hybrid training: DP x TP x PP + ZeRO + remat (BASELINE config 4
analog, tiny shapes). The reference's flagship hybrid is DP x PP with
colocated split (README.md:58-70); this exercises all three plus ZeRO."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import optax

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.models.gpt import gpt_loss
from easyparallellibrary_tpu.parallel import (
    TrainState, create_sharded_train_state, make_train_step, parallelize)


@pytest.mark.slow
def test_dp_tp_pp_zero_training():
  env = epl.init(epl.Config({"pipeline.num_micro_batch": 2,
                             "zero.level": "v1"}))
  cfg = GPTConfig(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
                  d_ff=64, max_seq_len=16, dtype=jnp.float32,
                  tensor_parallel=True, pipeline_stages=2,
                  num_micro_batch=2, remat=True, remat_policy="dots")
  with epl.replicate(1):
    model = GPT(cfg)
  with epl.replicate(1):
    pass
  with epl.split(2):
    pass
  plan = epl.current_plan()
  mesh = plan.build_mesh()
  sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
  assert sizes == {"stage": 2, "data": 2, "seq": 1, "expert": 1, "model": 2}

  # batch: micro-batches (2) x data shards (2) x 2 samples
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 17)),
                    jnp.int32)
  batch = {"ids": ids}
  tx = optax.adam(1e-2)

  def init_fn(rng):
    return TrainState.create(
        apply_fn=model.apply,
        params=model.init(rng, ids[:, :-1])["params"], tx=tx)

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0), zero_level="v1")

  # Pipeline stage params stacked + sharded over stage; TP kernels over
  # model; adam state sharded over data (ZeRO).
  qkv = state.params["pipeline"]["stages"]["stacked"]["block_0"][
      "attn"]["qkv"]["kernel"]
  assert qkv.names == ("stage", None, "model")
  leaf = qkv.value
  assert leaf.sharding.shard_shape(leaf.shape)[0] == 1       # stage-sharded
  assert leaf.sharding.shard_shape(leaf.shape)[2] == leaf.shape[2] // 2

  step = parallelize(
      make_train_step(lambda p, b, r: gpt_loss(model, p, b, r)),
      mesh, shardings)
  losses = []
  for _ in range(6):
    state, m = step(state, batch, jax.random.PRNGKey(1))
    losses.append(float(m["loss"]))
  assert np.isfinite(losses).all()
  assert losses[-1] < losses[0]


def test_hybrid_matches_pure_dp():
  """Same model/params trained on hybrid mesh == pure-DP numerics."""
  def run(hybrid):
    env = epl.init()
    cfg = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                    d_ff=64, max_seq_len=16, dtype=jnp.float32,
                    tensor_parallel=hybrid)
    if hybrid:
      with epl.split(4):
        pass
    mesh = epl.current_plan().build_mesh()
    model = GPT(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 17)),
                      jnp.int32)
    tx = optax.sgd(0.1)

    def init_fn(rng):
      return TrainState.create(
          apply_fn=model.apply,
          params=model.init(rng, ids[:, :-1])["params"], tx=tx)

    state, shardings = create_sharded_train_state(
        init_fn, mesh, jax.random.PRNGKey(3))
    step = parallelize(
        make_train_step(lambda p, b, r: gpt_loss(model, p, b, r)),
        mesh, shardings)
    out = []
    for _ in range(3):
      state, m = step(state, {"ids": ids}, jax.random.PRNGKey(1))
      out.append(float(m["loss"]))
    return out

  np.testing.assert_allclose(run(True), run(False), rtol=2e-3)


@pytest.mark.slow
def test_pp_seq_tp_compose():
  """Pipeline x sequence x tensor parallel on one mesh (stage2 x seq2 x
  model2, data=1): the full-axis composition compiles and trains."""
  env = epl.init(epl.Config({"sequence.parallelism": "ring",
                             "sequence.axis_size": 2,
                             "pipeline.num_micro_batch": 2}))
  cfg = GPTConfig(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
                  d_ff=64, max_seq_len=16, dtype=jnp.float32,
                  tensor_parallel=True, seq_parallel=True, attn_impl="ring",
                  pipeline_stages=2, num_micro_batch=2)
  with epl.replicate(1, name="s0"):
    pass
  with epl.replicate(1, name="s1"):
    pass
  with epl.split(2):
    pass
  model = GPT(cfg)
  mesh = epl.current_plan().build_mesh()
  sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
  assert (sizes["stage"], sizes["seq"], sizes["model"]) == (2, 2, 2)

  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4, 17)),
                    jnp.int32)
  tx = optax.adam(1e-2)

  def init_fn(rng):
    return TrainState.create(
        apply_fn=model.apply,
        params=model.init(rng, ids[:, :-1])["params"], tx=tx)

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  step = parallelize(
      make_train_step(lambda p, b, r: gpt_loss(model, p, b, r)),
      mesh, shardings)
  losses = []
  for _ in range(4):
    state, m = step(state, {"ids": ids}, jax.random.PRNGKey(1))
    losses.append(float(m["loss"]))
  assert np.isfinite(losses).all() and losses[-1] < losses[0]
