"""Fleet-grade observability (ISSUE 9): request-flow correlation, SLO
monitors, compile sentinel, anomaly-triggered deep capture.

The acceptance contract: a router kill episode exports a schema-valid
trace in which every migrated request is ONE connected flow (router
submit → first replica → failover → survivor retire),
``slo_events.jsonl`` records the breach window, and a diagnostic bundle
exists for the kill — while the fault-free guard shows bit-exact
streams, fused-step compile count 1, and the compile sentinel silent,
with the full layer enabled.  The quick trio below pins exactly that;
the units cover the rule engine, burn-rate windows, sentinel watermark,
capture rate limiting, reservoir determinism, and ``report --follow``.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.models.gpt import generate
from easyparallellibrary_tpu.observability import report
from easyparallellibrary_tpu.observability import slo as slo_lib
from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.observability.registry import MetricRegistry
from easyparallellibrary_tpu.observability.slo import (
    BurnRateRule, CompileSentinel, DiagnosticCapture, SLOMonitor,
    SLORule)
from easyparallellibrary_tpu.observability.trace import validate_trace
from easyparallellibrary_tpu.profiler.serving import (
    ServingStats, _Reservoir)
from easyparallellibrary_tpu.serving import (
    ContinuousBatchingEngine, Request, Router)
from easyparallellibrary_tpu.testing import chaos

TINY = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                 d_ff=64, max_seq_len=32, dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _drop_ambient_observability():
  """Ambient tracer/monitor outlive the per-test Env reset; drop both
  so later tests (and test files) start clean."""
  yield
  trace_lib.reset()
  slo_lib.reset()


def _model_and_params(cfg=TINY, seed=0):
  model = GPT(cfg)
  params = model.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, 4), jnp.int32))["params"]
  return model, params


def _oracle(model, params, prompt, max_new):
  return np.asarray(
      generate(model, params, jnp.asarray(prompt)[None], max_new))[0]


def _track_names(events):
  """tid -> thread-name from the export's metadata events."""
  return {e["tid"]: e["args"]["name"] for e in events
          if e.get("ph") == "M" and e.get("name") == "thread_name"}


# ---------------------------------------------------- quick acceptance


@pytest.mark.quick
def test_failover_flow_connected_breach_logged_bundle_captured(tmp_path):
  """THE acceptance episode: kill one of two replicas mid-decode with
  the full observability layer on.  Every request finishes bit-exact;
  each MIGRATED request's flow renders as one connected arc touching
  BOTH replicas' tracks; the trace passes the flow-aware validator;
  slo_events.jsonl records the replica_down breach window; a diagnostic
  bundle exists for the kill; and the compile sentinel stays silent
  through the whole join/leave/failover/rejoin episode (survivor's
  fused step still compiled once)."""
  events_path = str(tmp_path / "slo_events.jsonl")
  capture_dir = str(tmp_path / "diag")
  trace_path = str(tmp_path / "trace.json")
  epl.init(epl.Config({"observability": {
      "enabled": True,
      "slo": {"enabled": True, "events_path": events_path,
              "capture_dir": capture_dir,
              "capture_min_interval_s": 0.0}}}))
  tracer = trace_lib.ensure_configured()
  model, params = _model_and_params()
  r = np.random.RandomState(8)
  prompts = [r.randint(0, 64, (n,)).astype(np.int32)
             for n in (5, 3, 9, 2)]
  registry = MetricRegistry()
  router = Router(model, params, num_replicas=2, num_slots=2,
                  prefill_chunk=4, registry=registry)
  killer = chaos.ReplicaKiller(router.replicas[0].engine,
                               kill_calls=(3,))
  for i, p in enumerate(prompts):
    assert router.submit(Request(uid=i, prompt=p, max_new_tokens=6))
  assert {router.placement[i] for i in range(4)} == {0, 1}
  out = router.run()
  assert killer.kills == 1 and router.failovers == 1

  # Join/leave continued after the failover; now rejoin the corpse warm
  # (the breaker is force-overridden) and serve one more request — the
  # compile sentinel must stay silent across the WHOLE episode.
  assert router.rejoin(0, force=True)
  assert router.submit(Request(uid="post", prompt=prompts[0],
                               max_new_tokens=4))
  out.update(router.run())
  for rep in router.replicas:
    assert rep.engine._compile_sentinel.recompiles == 0
    assert rep.stats.recompiles == 0
  assert router.replicas[1].engine._step_fn._cache_size() == 1, \
      "failover/rejoin must not recompile the survivor's fused step"

  # Streams bit-exact vs the single-engine oracle, nothing lost.
  for i, p in enumerate(prompts):
    np.testing.assert_array_equal(out[i], _oracle(model, params, p, 6),
                                  err_msg=f"req {i}")

  # Schema-valid export, INCLUDING the flow schema (every flow ends).
  assert tracer.export(trace_path)
  events = validate_trace(trace_path)
  tracks = _track_names(events)

  # One flow per request: s at the router, f at retirement.
  flows = {}
  for ev in events:
    if ev.get("ph") in ("s", "t", "f"):
      flows.setdefault(ev["id"], []).append(ev)
  assert flows, "no request-flow events in the trace"
  for fid, evs in flows.items():
    phases = [e["ph"] for e in evs]
    assert phases[0] == "s" and phases[-1] == "f", (fid, phases)

  # Migrated requests: their flow arc must touch BOTH replicas' slot
  # tracks — router submit -> replica0 slot -> failover -> replica1.
  spans, _ = report.pair_spans(events)
  migrated_uids = {s["args"]["uid"] for s in spans
                   if s["args"].get("finish_reason") == "migrated"}
  assert migrated_uids, "the kill should have migrated requests"
  uid_flows = {}
  for ev in events:
    if ev.get("ph") == "s" and "args" in ev and "uid" in ev["args"]:
      uid_flows[ev["args"]["uid"]] = ev["id"]
  for uid in migrated_uids:
    evs = flows[uid_flows[uid]]
    names = {tracks.get(e["tid"], "") for e in evs}
    assert any(n.startswith("serving/replica0/slot") for n in names), \
        (uid, names)
    assert any(n.startswith("serving/replica1/slot") for n in names), \
        (uid, names)

  # The SLO monitor recorded the breach window in the machine-readable
  # log (the replica_down rule over the fleet rollup published AT the
  # failover, not a heartbeat later).
  slo_events = [json.loads(l) for l in open(events_path)]
  breaches = [e for e in slo_events if e["event"] == "breach"
              and e["rule"] == "replica_down"]
  assert breaches and breaches[0]["value"] == 1.0
  # The warm rejoin closed the window.
  recoveries = [e for e in slo_events if e["event"] == "recover"
                and e["rule"] == "replica_down"]
  assert recoveries, "rejoin should have recorded the recovery"

  # A diagnostic bundle exists for the kill: staged+renamed (no .tmp),
  # carrying the trace tail, registry snapshot and engine summaries.
  bundles = sorted(os.listdir(capture_dir))
  assert bundles and not any(b.endswith(".tmp") for b in bundles)
  bundle = os.path.join(capture_dir, bundles[0])
  contents = set(os.listdir(bundle))
  assert {"meta.json", "trace.json", "registry.json"} <= contents
  meta = json.load(open(os.path.join(bundle, "meta.json")))
  assert meta["reason"] == "replica_down"
  if "state.json" in contents:
    state = json.load(open(os.path.join(bundle, "state.json")))
    assert any(k.startswith("serving/replica") for k in state)
  router.close()


@pytest.mark.quick
def test_slo_monitor_fault_free_bit_exact_zero_recompile(tmp_path):
  """Fault-free guard: serving with the FULL layer enabled (tracer +
  SLO monitor + registry + compile sentinel + deep capture armed) is
  bit-identical to the bare baseline, with the fused step still
  compiled once and zero sentinel flags — monitoring never changes what
  it monitors."""
  cfg = GPTConfig(vocab_size=64, num_layers=1, num_heads=4, d_model=32,
                  d_ff=64, max_seq_len=32, dtype=jnp.float32)
  model, params = _model_and_params(cfg)
  r = np.random.RandomState(5)
  prompts = [r.randint(0, 64, (n,)).astype(np.int32)
             for n in (5, 3, 6, 2)]

  def drive(eng):
    for i in range(2):
      assert eng.submit(Request(uid=i, prompt=prompts[i],
                                max_new_tokens=5 + i))
    out = {}
    for _ in range(2):
      for fin in eng.step():
        out[fin.uid] = fin.tokens
    for i in range(2, 4):
      assert eng.submit(Request(uid=i, prompt=prompts[i],
                                max_new_tokens=5 + i))
    out.update(eng.run())
    return out

  epl.init()
  base = drive(ContinuousBatchingEngine(model, params, num_slots=2,
                                        prefill_chunk=4))
  epl.init(epl.Config({"observability": {
      "enabled": True,
      "slo": {"enabled": True, "ttft_p99_s": 30.0, "itl_p99_s": 30.0,
              "shed_objective": 0.99,
              "events_path": str(tmp_path / "slo.jsonl"),
              "capture_dir": str(tmp_path / "diag")}}}))
  eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                 prefill_chunk=4, stats=ServingStats(),
                                 registry=MetricRegistry())
  monitored = drive(eng)
  monitor = slo_lib.get_monitor()
  assert monitor is not None
  assert eng._step_fn._cache_size() == 1
  assert eng._compile_sentinel.recompiles == 0
  # The monitor really evaluated this run's records (per-step via the
  # registry sink, percentile rollups at run() end) — and a healthy
  # fault-free run breached nothing.
  assert any(key.startswith(("ttft_p99", "itl_p99"))
             for key in monitor.status()), monitor.status()
  assert monitor.breaches == 0
  assert sorted(base) == sorted(monitored)
  for i in base:
    np.testing.assert_array_equal(monitored[i], base[i],
                                  err_msg=f"req {i}")


def test_engine_publishes_percentile_rollups_mid_run():
  """Review fix: per-step records carry only step-local gauges, so the
  TTFT/ITL SLO rules need the PERIODIC stats rollup — published every
  50 engine steps — to stay live on an engine driven by step() forever
  (a router replica never calls run(), whose end-of-drive publish was
  previously the only rollup)."""
  class _Sink:
    def __init__(self):
      self.records = []

    def write(self, step, metrics):
      self.records.append(dict(metrics))

    def flush(self):
      pass

    def close(self):
      pass

  cfg = GPTConfig(vocab_size=64, num_layers=1, num_heads=4, d_model=32,
                  d_ff=64, max_seq_len=128, dtype=jnp.float32)
  model, params = _model_and_params(cfg)
  epl.init(epl.Config({"observability": {"slo": {
      "enabled": True, "ttft_p99_s": 60.0}}}))
  sink = _Sink()
  eng = ContinuousBatchingEngine(model, params, num_slots=1,
                                 prefill_chunk=4, stats=ServingStats(),
                                 registry=MetricRegistry(sink))
  eng.submit(Request(uid="a", prompt=np.arange(4, dtype=np.int32),
                     max_new_tokens=70))
  while eng.has_work:   # step() directly — run()'s end publish never fires
    eng.step()
  rollups = [r for r in sink.records if "serving/ttft_p99_s" in r]
  assert rollups, "no mid-run percentile rollup reached the registry"
  monitor = slo_lib.get_monitor()
  assert any(key.startswith("ttft_p99") for key in monitor.status())
  assert monitor.breaches == 0


# ------------------------------------------------------------ rule units


def test_slo_threshold_rule_streak_and_recovery():
  m = SLOMonitor([SLORule("ttft", "ttft_p99_s", "<=", 0.5,
                          for_records=2)])
  m.observe(1, {"serving/ttft_p99_s": 0.9})
  assert m.breaches == 0          # debounce: one bad record is noise
  m.observe(2, {"serving/ttft_p99_s": 0.9})
  assert m.breaches == 1
  m.observe(3, {"serving/ttft_p99_s": 0.9})
  assert m.breaches == 1          # still the same breach window
  m.observe(4, {"serving/ttft_p99_s": 0.1})
  assert m.recoveries == 1
  m.observe(5, {"serving/ttft_p99_s": 0.9})
  m.observe(6, {"serving/ttft_p99_s": 0.9})
  assert m.breaches == 2          # a fresh window needs a fresh streak


def test_slo_rule_suffix_matching_tracks_separate_streams():
  m = SLOMonitor([SLORule("itl", "itl_p99_s", "<=", 0.1)])
  m.observe(1, {"serving/fleet/itl_p99_s": 0.5,
                "serving/replica0/itl_p99_s": 0.05})
  assert m.breaches == 1
  assert m.status() == {"itl@serving/fleet/itl_p99_s": "breach",
                        "itl@serving/replica0/itl_p99_s": "ok"}


def test_burn_rate_rule_fast_and_slow_windows():
  rule = BurnRateRule("shed", bad="shed", good="finished_requests",
                      objective=0.9, fast_window=2, slow_window=6,
                      fast_burn=3.0, slow_burn=2.0)
  m = SLOMonitor([rule])
  shed, fin = 0.0, 0.0
  # Healthy traffic: 2% shed against a 10% budget -> burn 0.2x.
  for step in range(7):
    shed += 1
    fin += 49
    m.observe(step, {"serving/fleet/shed": shed,
                     "serving/fleet/finished_requests": fin})
  assert m.breaches == 0
  # A short spike trips the fast window but not the slow one: no page.
  m.observe(7, {"serving/fleet/shed": shed + 30,
                "serving/fleet/finished_requests": fin + 20})
  assert m.breaches == 0
  # Sustained 60% shedding: both windows exceed -> breach, then
  # recovery once the fast window is clean again.
  for step in range(8, 14):
    shed += 30
    fin += 20
    m.observe(step, {"serving/fleet/shed": shed,
                     "serving/fleet/finished_requests": fin})
  assert m.breaches == 1
  for step in range(14, 18):
    fin += 50
    m.observe(step, {"serving/fleet/shed": shed,
                     "serving/fleet/finished_requests": fin})
  assert m.recoveries == 1


def test_raising_listener_is_isolated_logged_once_and_counted():
  """ISSUE 13 satellite: a raising listener callback is caught, logged
  ONCE per listener, counted (slo/listener_errors), and never breaks
  monitoring, the caller's step, or SIBLING listeners — today's
  actuators subscribe here, and one bad subscriber must not take the
  serving loop down with it."""
  import logging

  from easyparallellibrary_tpu.utils.logging import get_logger
  m = SLOMonitor([SLORule("ttft", "ttft_p99_s", "<=", 0.1)])
  heard = []

  def bad_listener(name, payload):
    raise RuntimeError("chaos: broken subscriber")

  m.add_listener(bad_listener)
  m.add_listener(lambda name, payload: heard.append(name))
  captured = []

  class _Capture(logging.Handler):
    def emit(self, record):
      captured.append(record.getMessage())

  handler = _Capture()
  get_logger().addHandler(handler)  # the package logger: propagate off
  try:
    for step in range(4):
      # Breach -> recover -> breach -> recover: two breach deliveries.
      m.observe(step, {"serving/ttft_p99_s": 9.0 if step % 2 == 0
                       else 0.01})
  finally:
    get_logger().removeHandler(handler)
  assert m.breaches == 2
  # The sibling heard EVERY breach despite the raiser running first.
  assert heard == ["ttft", "ttft"]
  assert m.listener_errors == 2
  # Logged once per listener, not once per failure.
  logged = [msg for msg in captured
            if "listener" in msg and "broken subscriber" in msg]
  assert len(logged) == 1
  # note_event breaches go through the same isolation.
  m.note_event("watchdog_timeout", {"twin": "serving/fused_step"})
  assert m.listener_errors == 3 and heard[-1] == "watchdog_timeout"


def test_follow_renders_actuation_events(tmp_path):
  """ISSUE 13 satellite: report --follow shows actuations (knob moved,
  old->new value, triggering rule) in the live SLO panel."""
  metrics = tmp_path / "metrics.jsonl"
  slo = tmp_path / "slo_events.jsonl"
  metrics.write_text("")
  m = SLOMonitor([], events_path=str(slo))
  m.note_actuation("autotune", {
      "actuator": "autotune", "rule": "shed_burn",
      "from_level": "normal", "to_level": "spec_trim",
      "knobs": {"tune_spec_k": [-1, 2]}}, step=7)
  m.note_actuation("autoscale", {
      "actuator": "autoscale", "action": "scale_up", "replica": 2,
      "rule": "shed_burn", "knobs": {"live_replicas": [2, 3]}},
      step=9)
  m.close()
  assert m.actuations == 2
  st = report.FollowState(str(metrics), str(slo))
  block = st.poll()
  assert block is not None
  assert st.actuation_count == 2
  assert "actuations [2 total]" in block
  assert "autotune: tune_spec_k -1->2 (rule shed_burn)" in block
  assert "autoscale: live_replicas 2->3 (rule shed_burn)" in block
  # Actuations are not breach streams: the SLO panel stays clean.
  assert st.slo_breaches == 0 and st.slo_state == {}


def test_breach_pressure_freshness_is_per_stream():
  """ISSUE 13 hardening: BreachPressure judges liveness per stream —
  one stream RECOVERING shrinks the breached set without a single new
  record on the wedged survivors, and must not read as fresh pressure
  (an aggregate-sum check would misfire exactly as the system
  recovers)."""
  class FakeMon:
    def __init__(self):
      self.streams = {}

    def breached_stream_obs(self):
      return dict(self.streams)

  mon = FakeMon()
  probe = slo_lib.BreachPressure(mon, lambda rule, key: True)
  assert probe.poll() == (False, False)
  mon.streams = {("b", "x"): 3, ("b", "y"): 5}
  assert probe.poll() == (True, True)          # new breached streams
  assert probe.poll() == (True, False)         # nothing grew
  mon.streams = {("b", "x"): 3}                # y recovered: sum shrank
  assert probe.poll() == (True, False), \
      "a recovery must not read as fresh pressure"
  mon.streams = {("b", "x"): 4}
  assert probe.poll() == (True, True)          # x's records flowed
  mon.streams = {}
  assert probe.poll() == (False, False)
  mon.streams = {("b", "z"): 1}                # fresh breached stream
  assert probe.poll() == (True, True)
  assert slo_lib.BreachPressure(None, lambda r, k: True).poll() == \
      (False, False)


def test_monitor_skips_device_arrays_and_idle_burn_windows():
  """Raw registry pass-through can carry device arrays; evaluating one
  would force the host sync the sinks defer — they must be skipped, not
  floated.  And a burn rule with zero traffic renders no verdict."""
  m = SLOMonitor([SLORule("loss", "loss", "<=", 0.1),
                  BurnRateRule("b", bad="shed", good="finished_requests",
                               objective=0.9, fast_window=1,
                               slow_window=2)])
  dev = jnp.asarray(5.0)          # would breach if evaluated
  for step in range(4):
    m.observe(step, {"train/loss": dev, "serving/shed": 0.0,
                     "serving/finished_requests": 0.0})
  assert m.breaches == 0
  assert m.status().get("loss@train/loss") is None  # never evaluated


def test_rules_from_config_and_validation():
  conf = epl.Config({"observability": {"slo": {
      "enabled": True, "ttft_p99_s": 0.5, "itl_p99_s": 0.05,
      "shed_objective": 0.95}}})
  names = [r.name for r in
           slo_lib.rules_from_config(conf.observability.slo)]
  assert names == ["ttft_p99", "itl_p99", "shed_burn", "replica_down"]
  conf2 = epl.Config({"observability": {"slo": {
      "enabled": True, "replicas_down": False}}})
  assert [r.name for r in
          slo_lib.rules_from_config(conf2.observability.slo)] == []
  with pytest.raises(ValueError, match="shed_objective"):
    epl.Config({"observability.slo.shed_objective": 1.0})
  with pytest.raises(ValueError, match="fast_window"):
    epl.Config({"observability": {"slo": {"fast_window": 9,
                                          "slow_window": 3}}})
  with pytest.raises(ValueError, match="capture_limit"):
    epl.Config({"observability.slo.capture_limit": 0})
  with pytest.raises(ValueError, match="ttft_p99_s"):
    epl.Config({"observability.slo.ttft_p99_s": -1.0})


def test_ensure_configured_ambient_and_explicit_install():
  slo_lib.reset()
  epl.init(epl.Config({"observability": {"slo": {
      "enabled": True, "ttft_p99_s": 1.0}}}))
  m1 = slo_lib.ensure_configured()
  assert m1 is not None and [r.name for r in m1.rules] == [
      "ttft_p99", "replica_down"]
  assert slo_lib.ensure_configured() is m1
  # A component's foreign config (slo off there) must not tear down the
  # run's monitor — same contract as the tracer's ensure_configured.
  foreign = epl.Config({"serving.num_slots": 2})
  assert slo_lib.ensure_configured(foreign) is m1
  epl.init()                      # ambient off -> torn down
  assert slo_lib.ensure_configured() is None
  mine = SLOMonitor([])
  slo_lib.install(mine)
  epl.init()
  assert slo_lib.ensure_configured() is mine  # explicit install wins
  slo_lib.reset()


# ------------------------------------------------- sentinel & capture


def test_compile_sentinel_watermark_and_attribution():
  sizes = iter([1, 1, 3, 3, 4])
  fired = []
  s = CompileSentinel("twin", lambda: next(sizes),
                      on_recompile=[lambda *a: fired.append(a)])
  assert s.check() == 0           # warmup compile is expected
  assert s.check() == 0
  assert s.check(lambda: {"tokens": "int32[2,4]"}) == 2
  assert s.check() == 0           # watermark moved; no re-fire
  assert s.check() == 1
  assert s.recompiles == 3
  assert fired[0][:3] == ("twin", 3, 2)
  assert fired[0][3] == {"tokens": "int32[2,4]"}


def test_compile_sentinel_survives_unreadable_cache():
  def boom():
    raise AttributeError("no _cache_size on this callable")
  s = CompileSentinel("twin", boom)
  assert s.check() == 0 and s.check() == 0  # degrades, never raises


def test_sentinel_breach_reaches_monitor_and_capture(tmp_path):
  cap = DiagnosticCapture(str(tmp_path), min_interval_s=0.0)
  m = SLOMonitor([], events_path=str(tmp_path / "ev.jsonl"),
                 capture=cap)
  heard = []
  m.add_listener(lambda name, payload: heard.append((name, payload)))
  sizes = iter([1, 2])
  s = CompileSentinel(
      "fused_step", lambda: next(sizes),
      on_recompile=[lambda label, size, extra, sig: m.note_event(
          "unexpected_recompile",
          {"twin": label, "cache_size": size, "signature": str(sig)})])
  s.check()
  s.check(lambda: "f32[4,8]")
  assert m.breaches == 1
  assert heard and heard[0][0] == "unexpected_recompile"
  (line,) = [json.loads(l) for l in open(tmp_path / "ev.jsonl")]
  assert line["rule"] == "unexpected_recompile"
  assert line["signature"] == "f32[4,8]"
  assert any(d.startswith("bundle_") for d in os.listdir(tmp_path))
  m.close()


def test_diagnostic_capture_rate_limit_and_retention(tmp_path):
  t = [0.0]
  cap = DiagnosticCapture(str(tmp_path), limit=2, min_interval_s=10.0,
                          clock=lambda: t[0])
  assert cap.capture("first") is not None
  assert cap.capture("suppressed") is None      # inside the interval
  assert cap.suppressed == 1
  for i in range(3):
    t[0] += 11.0
    assert cap.capture(f"later{i}") is not None
  bundles = sorted(os.listdir(tmp_path))
  assert len(bundles) == 2                      # retention bound
  assert all(not b.endswith(".tmp") for b in bundles)
  assert "later2" in bundles[-1]                # oldest evicted first


# ------------------------------------------------- reservoir & follow


def test_reservoir_deterministic_and_capped():
  a, b = _Reservoir(8), _Reservoir(8)
  for i in range(1000):
    a.add(float(i))
    b.add(float(i))
  assert a.items == b.items                     # deterministic
  assert len(a.items) == 8 and a.count == 1000
  assert all(0 <= x < 1000 for x in a.items)
  small = _Reservoir(8)
  for i in range(5):
    small.add(float(i))
  assert small.items == [0.0, 1.0, 2.0, 3.0, 4.0]  # exact below cap


def test_serving_stats_samples_bounded_and_merge_bounded():
  t = [0.0]
  stats = ServingStats(clock=lambda: t[0], sample_limit=16)
  for i in range(200):
    uid = f"r{i}"
    stats.note_submitted(uid)
    t[0] += 0.01
    stats.note_first_token(uid)
    t[0] += 0.05
    stats.note_finished(uid, new_tokens=3)
  assert len(stats.ttft_samples()) == 16
  assert len(stats.itl_samples()) == 16
  assert stats.finished_requests == 200         # aggregates keep all
  s = stats.summary()
  assert s["ttft_p50_s"] == pytest.approx(0.01)
  assert s["itl_p50_s"] == pytest.approx(0.025)
  from easyparallellibrary_tpu.profiler.serving import fleet_summary
  fleet = fleet_summary([stats, stats])
  assert fleet["ttft_p50_s"] == pytest.approx(0.01)


def test_report_follow_tails_metrics_and_slo(tmp_path):
  metrics = tmp_path / "metrics.jsonl"
  slo = tmp_path / "slo_events.jsonl"
  metrics.write_text(json.dumps({
      "step": 3, "serving/fleet/replicas": 2.0,
      "serving/fleet/tokens_per_s": 42.0,
      "serving/fleet/replicas_healthy": 2.0}) + "\n")
  slo.write_text("")
  st = report.FollowState(str(metrics), str(slo))
  first = st.poll()
  assert first is not None and "42.0 tok/s" in first
  assert "no events" in first
  assert st.poll() is None                      # nothing new
  # Records append mid-run — including a PARTIAL trailing line, which
  # must wait for its newline instead of being half-parsed.
  with open(metrics, "a") as f:
    f.write(json.dumps({"step": 9, "serving/fleet/replicas": 2.0,
                        "serving/fleet/tokens_per_s": 77.0,
                        "serving/fleet/replicas_down": 1.0}) + "\n")
    f.write('{"step": 10, "serving/fl')          # mid-write
  with open(slo, "a") as f:
    f.write(json.dumps({"time": 1.0, "event": "breach",
                        "rule": "replica_down",
                        "metric": "serving/fleet/replicas_down",
                        "value": 1.0, "target": 0.0}) + "\n")
  second = st.poll()
  assert second is not None and "77.0 tok/s" in second
  assert "replica_down@serving/fleet/replicas_down: BREACH" in second
  assert st.records == 2                        # partial line not eaten
  # The CLI entry point drives the same machinery.
  assert report.main(["--follow", str(metrics), "--slo", str(slo),
                      "--max-polls", "1", "--interval", "0"]) == 0


def test_validate_trace_flow_negatives():
  base = {"pid": 0, "tid": 0, "cat": "serving"}
  with pytest.raises(ValueError, match="never terminated"):
    validate_trace([{"ph": "s", "name": "flow", "ts": 1.0, "id": 7,
                     **base}])
  with pytest.raises(ValueError, match="no open flow"):
    validate_trace([{"ph": "t", "name": "flow", "ts": 1.0, "id": 7,
                     **base}])
  with pytest.raises(ValueError, match="no open flow"):
    validate_trace([{"ph": "f", "name": "flow", "ts": 1.0, "id": 7,
                     **base}])
  with pytest.raises(ValueError, match="started again"):
    validate_trace([
        {"ph": "s", "name": "flow", "ts": 1.0, "id": 7, **base},
        {"ph": "s", "name": "flow", "ts": 2.0, "id": 7, **base},
        {"ph": "f", "name": "flow", "ts": 3.0, "id": 7, **base}])
  with pytest.raises(ValueError, match="missing 'id'"):
    validate_trace([{"ph": "s", "name": "flow", "ts": 1.0, **base}])
  # A complete s -> t -> f flow (id reused AFTER termination) is valid.
  validate_trace([
      {"ph": "s", "name": "flow", "ts": 1.0, "id": 7, **base},
      {"ph": "t", "name": "flow", "ts": 2.0, "id": 7, **base},
      {"ph": "f", "name": "flow", "ts": 3.0, "id": 7, **base},
      {"ph": "s", "name": "flow", "ts": 4.0, "id": 7, **base},
      {"ph": "f", "name": "flow", "ts": 5.0, "id": 7, **base}])


# -------------------------------------------- virtual-clock discipline


def test_slo_monitor_and_capture_follow_installed_vclock(tmp_path):
  """The SLO layer's default clocks are utils/vclock seams consulted at
  CALL time — install a virtual clock (what the fleet simulator and
  the golden recorder do) and a config-built monitor stamps events, a
  default-constructed DiagnosticCapture debounces, and bundle names
  timestamp, all in SIMULATED seconds.  This is the contract replay
  fidelity (tests/test_sim_replay.py) rests on: breach windows and
  capture rate limits must not read the host's clocks behind the
  episode's back."""
  from easyparallellibrary_tpu.sim.engine import SimClock
  from easyparallellibrary_tpu.utils import vclock
  clk = SimClock()
  clk.advance(1000.0)
  vclock.install(clk)
  try:
    epl.init(epl.Config({"observability": {"slo": {
        "enabled": True, "ttft_p99_s": 0.5}}}))
    m = slo_lib.ensure_configured()
    m.observe(1, {"serving/fleet/ttft_p99_s": 0.9})
    assert m.breaches == 1
    assert m.events[-1]["time"] == 1000.0       # sim seconds, not wall
    clk.advance(7.0)
    m.observe(2, {"serving/fleet/ttft_p99_s": 0.1})
    assert m.recoveries == 1
    assert m.events[-1]["time"] == 1007.0
    cap = DiagnosticCapture(str(tmp_path), min_interval_s=30.0)
    first = cap.capture("vclock")
    assert first is not None
    assert os.path.basename(first).startswith("bundle_1007_")
    assert cap.capture("same-instant") is None  # debounced in sim time
    assert cap.suppressed == 1
    clk.advance(31.0)
    assert cap.capture("later") is not None
  finally:
    vclock.reset()


def test_burn_windows_fill_on_record_count_with_frozen_clock():
  """Burn-rate windows are RECORD-count windows, not wall-time windows:
  with the virtual clock frozen at 0 the breach still fires, exactly
  when the slow window fills (slow_window + 1 cumulative records).
  This count-driven property is what makes a fixed-dt replay's breach
  timing deterministic."""
  from easyparallellibrary_tpu.sim.engine import SimClock
  from easyparallellibrary_tpu.utils import vclock
  clk = SimClock()                 # never advanced
  vclock.install(clk)
  try:
    rule = BurnRateRule("shed_burn", bad="shed",
                        good="finished_requests", objective=0.9,
                        fast_window=3, slow_window=6,
                        fast_burn=1.0, slow_burn=1.0)
    m = SLOMonitor([rule])
    shed = good = 0
    breach_at = None
    for i in range(1, 12):
      shed += 5
      good += 5                    # 50% bad vs a 10% budget: burn 5x
      m.observe(i, {"serving/fleet/shed": float(shed),
                    "serving/fleet/finished_requests": float(good)})
      if m.breaches and breach_at is None:
        breach_at = i
    assert breach_at == rule.slow_window + 1
    assert m.events[-1]["time"] == 0.0          # frozen clock honored
  finally:
    vclock.reset()


def test_slo_module_never_reads_host_clocks_directly():
  """Source-level pin for the vclock discipline: every timestamp in
  observability/slo.py must flow through utils/vclock (or an injected
  clock), never a literal host-clock call — a single stray
  time.time() would silently desynchronize simulated episodes."""
  import inspect
  src = inspect.getsource(slo_lib)
  for banned in ("time.time(", "time.monotonic(", "time.perf_counter("):
    assert banned not in src, banned
