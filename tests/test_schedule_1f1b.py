"""True-1F1B schedule tests (reference analog: tests/scheduler_test.py —
the PreferBackward policy that orders backward-k before forward-k+1,
epl/strategies/scheduler.py:53-116).

Covers: numeric equivalence of the interleaved-schedule gradients against
plain autodiff, GPT integration, the live-activation memory bound vs the
GPipe (PreferForward) path, and schedule dispatch."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.models.gpt import (
    gpt_loss, make_gpt_1f1b_grad_fn, make_gpt_train_step)
from easyparallellibrary_tpu.parallel.schedule_1f1b import (
    one_f_one_b, split_micro_batches)


def _toy_fns(D=8):
  def feed_fn(fp, mb, rng):
    return jnp.tanh(mb["x"] @ fp["We"])

  def stage_fn(pr, x, rng):
    return jnp.tanh(x @ pr["W"]), jnp.float32(0)

  def emit_fn(ep, y, mb, rng):
    pred = y @ ep["Wo"]
    return jnp.mean((pred - mb["y"]) ** 2), {"pred_mean": jnp.mean(pred)}

  return feed_fn, stage_fn, emit_fn


@pytest.mark.parametrize("S,M", [(4, 6), (1, 4), (4, 1), (4, 2), (2, 8)])
def test_1f1b_engine_matches_autodiff(S, M):
  """Interleaved gradients == plain reverse-mode over the same pipeline,
  across steady-state, degenerate, and M < in-flight-window shapes."""
  epl.init()
  D = 8
  r = np.random.RandomState(0)
  feed_p = {"We": jnp.asarray(r.randn(D, D) * 0.3, jnp.float32)}
  stage_p = {"W": jnp.asarray(r.randn(S, D, D) * 0.3, jnp.float32)}
  emit_p = {"Wo": jnp.asarray(r.randn(D, 1) * 0.3, jnp.float32)}
  B = M * 2
  batch = {"x": jnp.asarray(r.randn(B, D), jnp.float32),
           "y": jnp.asarray(r.randn(B, 1), jnp.float32)}
  feed_fn, stage_fn, emit_fn = _toy_fns()
  mbs = split_micro_batches(batch, M)

  def ref_loss(fp, sp, ep, mbs):
    def per_mb(mb):
      x = feed_fn(fp, mb, None)
      for s in range(S):
        x, _ = stage_fn(jax.tree_util.tree_map(lambda a: a[s], sp), x, None)
      return emit_fn(ep, x, mb, None)[0]
    return jnp.mean(jax.vmap(per_mb)(mbs))

  ref_l, ref_g = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(
      feed_p, stage_p, emit_p, mbs)

  engine = one_f_one_b(feed_fn, stage_fn, emit_fn, S, M)
  (loss, aux), grads = jax.jit(engine)(feed_p, stage_p, emit_p, mbs, None)
  np.testing.assert_allclose(float(ref_l), float(loss), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
      ref_g, grads)


def _gpt_setup(M=4, dropout=0.0, **kw):
  env = epl.init()
  mesh = env.cluster.build_mesh(stage=2)
  base = dict(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
              d_ff=64, max_seq_len=16, dtype=jnp.float32,
              pipeline_stages=2, num_micro_batch=M,
              dropout_rate=dropout)
  base.update(kw)
  pp = GPT(GPTConfig(**base))
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4 * M, 17)),
                    jnp.int32)
  params = pp.init(jax.random.PRNGKey(0), ids[:, :-1])["params"]
  return mesh, pp, base, ids, params


@pytest.mark.slow
def test_gpt_1f1b_matches_autodiff():
  """1F1B GPT gradients == autodiff through the sequential ground truth."""
  mesh, pp, base, ids, params = _gpt_setup()
  seq = GPT(GPTConfig(**base, pipeline_debug_sequential=True))

  grad_1f1b = make_gpt_1f1b_grad_fn(pp)
  (l1, _), g1 = jax.jit(lambda p: grad_1f1b(p, {"ids": ids}, None))(params)

  def seq_loss(p):
    return gpt_loss(seq, p, {"ids": ids})[0]

  l2, g2 = jax.jit(jax.value_and_grad(seq_loss))(params)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-3, atol=1e-5),
      g1, g2)


@pytest.mark.slow
def test_gpt_1f1b_train_step_decreases_loss():
  """End-to-end: schedule dispatch + sharded training on the stage mesh."""
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state, parallelize)

  env = epl.init(epl.Config({"pipeline.strategy": "PreferBackward"}))
  mesh = env.cluster.build_mesh(stage=2)
  base = dict(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
              d_ff=64, max_seq_len=16, dtype=jnp.float32,
              pipeline_stages=2, num_micro_batch=4)
  model = GPT(GPTConfig(**base))
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (16, 17)),
                    jnp.int32)

  def init_fn(rng):
    return TrainState.create(
        apply_fn=model.apply,
        params=model.init(rng, ids[:, :-1])["params"], tx=optax.adam(1e-2))

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  step = parallelize(make_gpt_train_step(model), mesh, shardings)
  losses = []
  for i in range(8):
    state, m = step(state, {"ids": ids}, jax.random.PRNGKey(i))
    losses.append(float(m["loss"]))
  assert losses[-1] < losses[0]


@pytest.mark.slow
def test_gpt_train_step_dispatch():
  """PreferForward -> autodiff path; PreferBackward -> 1F1B engine."""
  _, pp, base, ids, params = _gpt_setup()
  fwd_cfg = epl.Config({"pipeline.strategy": "PreferForward"})
  bwd_cfg = epl.Config({"pipeline.strategy": "PreferBackward"})
  # Loss from both dispatch targets must agree (same params, same data).
  epl.init(fwd_cfg)
  epl.init().cluster.build_mesh(stage=2)
  step_fwd = make_gpt_train_step(pp, config=fwd_cfg)
  step_bwd = make_gpt_train_step(pp, config=bwd_cfg)
  state = __import__(
      "easyparallellibrary_tpu.parallel", fromlist=["TrainState"]
  ).TrainState.create(apply_fn=pp.apply, params=params, tx=optax.sgd(0.0))
  _, m_fwd = jax.jit(step_fwd)(state, {"ids": ids}, None)
  _, m_bwd = jax.jit(step_bwd)(state, {"ids": ids}, None)
  np.testing.assert_allclose(float(m_fwd["loss"]), float(m_bwd["loss"]),
                             rtol=1e-5)


@pytest.mark.slow
def test_1f1b_bounds_live_activations_vs_gpipe():
  """The VERDICT done-criterion: PreferBackward (1F1B) compiled temp bytes
  < PreferForward (GPipe, no remat) at M=8, S=4 — the schedule's
  live-activation bound, not just remat."""
  from easyparallellibrary_tpu.parallel import TrainState

  env = epl.init()
  mesh = env.cluster.build_mesh(stage=4)
  M = 8
  base = dict(vocab_size=64, num_layers=4, num_heads=4, d_model=64,
              d_ff=128, max_seq_len=32, dtype=jnp.float32,
              pipeline_stages=4, num_micro_batch=M)
  model = GPT(GPTConfig(**base))
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2 * M, 33)),
                    jnp.int32)
  params = model.init(jax.random.PRNGKey(0), ids[:, :-1])["params"]
  state = TrainState.create(apply_fn=model.apply, params=params,
                            tx=optax.sgd(0.1))

  step_fwd = make_gpt_train_step(
      model, config=epl.Config({"pipeline.strategy": "PreferForward"}))
  step_bwd = make_gpt_train_step(
      model, config=epl.Config({"pipeline.strategy": "PreferBackward"}))

  def temp_bytes(step):
    lowered = jax.jit(step).lower(state, {"ids": ids}, None)
    mem = lowered.compile().memory_analysis()
    return mem.temp_size_in_bytes

  b_fwd = temp_bytes(step_fwd)
  b_bwd = temp_bytes(step_bwd)
  assert b_bwd < b_fwd, (b_bwd, b_fwd)


def test_stageblocks_mask_applies_exact_count():
  """StageBlocks with n_active=k == StageBlocks(blocks_per_stage=k) on the
  matching param subset — masked slots are true identities."""
  epl.init()
  cfg = GPTConfig(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
                  d_ff=64, max_seq_len=16, dtype=jnp.float32)
  from easyparallellibrary_tpu.models.gpt import StageBlocks
  x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 32), jnp.float32)
  big = StageBlocks(cfg, blocks_per_stage=3)
  small = StageBlocks(cfg, blocks_per_stage=2)
  params = big.init(jax.random.PRNGKey(0), x)["params"]
  sub = {k: v for k, v in params.items() if k in ("block_0", "block_1")}
  out_masked = big.apply({"params": params}, x, 2)
  out_small = small.apply({"params": sub}, x)
  np.testing.assert_allclose(np.asarray(out_masked), np.asarray(out_small),
                             rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_gpt_uneven_layers_pipeline_and_1f1b_match_sequential():
  """num_layers % stages != 0 trains: both the GPipe module path and the
  1F1B engine agree with the sequential ground truth (VERDICT item 5;
  reference analog: arbitrary per-stage subgraphs,
  epl/parallel/graph_editor.py:423-443)."""
  env = epl.init()
  mesh = env.cluster.build_mesh(stage=2)
  base = dict(vocab_size=64, num_layers=5, num_heads=4, d_model=32,
              d_ff=64, max_seq_len=16, dtype=jnp.float32,
              pipeline_stages=2, num_micro_batch=4)
  pp = GPT(GPTConfig(**base))
  seq = GPT(GPTConfig(**base, pipeline_debug_sequential=True))
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (16, 17)),
                    jnp.int32)
  params = pp.init(jax.random.PRNGKey(0), ids[:, :-1])["params"]
  # ceil(5/2)=3 block slots per stage; stage 0 active=3, stage 1 active=2.
  stacked = params["pipeline"]["stages"]["stacked"]
  assert "block_2" in stacked

  l_pp, _ = jax.jit(lambda p: gpt_loss(pp, p, {"ids": ids}))(params)
  l_seq, _ = jax.jit(lambda p: gpt_loss(seq, p, {"ids": ids}))(params)
  np.testing.assert_allclose(float(l_pp), float(l_seq), rtol=1e-5)

  g_seq = jax.jit(jax.grad(lambda p: gpt_loss(seq, p, {"ids": ids})[0]))(
      params)
  grad_1f1b = make_gpt_1f1b_grad_fn(pp)
  (l1, _), g1 = jax.jit(lambda p: grad_1f1b(p, {"ids": ids}, None))(params)
  np.testing.assert_allclose(float(l1), float(l_seq), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-3, atol=1e-5),
      g1, g_seq)


@pytest.mark.slow
def test_1f1b_composes_amp_and_grouped_apply():
  """AMP loss scaling and PreferBackwardOptimizer's grouped apply compose
  around the 1F1B gradient path via build_train_step."""
  from easyparallellibrary_tpu.runtime.trainer import create_train_state

  amp_cfg = epl.Config({"amp.level": "O1", "amp.loss_scale": "128",
                        "pipeline.strategy": "PreferBackwardOptimizer"})
  env = epl.init(amp_cfg)
  env.cluster.build_mesh(stage=2)
  base = dict(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
              d_ff=64, max_seq_len=16, dtype=jnp.float32,
              pipeline_stages=2, num_micro_batch=4)
  model = GPT(GPTConfig(**base))
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (16, 17)),
                    jnp.int32)
  params = model.init(jax.random.PRNGKey(0), ids[:, :-1])["params"]
  state = create_train_state(model.apply, params, optax.sgd(1e-2),
                             config=amp_cfg)
  step = make_gpt_train_step(model, config=amp_cfg)
  new_state, m = jax.jit(step)(state, {"ids": ids}, None)
  assert float(m["loss_scale"]) == 128.0
  assert float(m["grads_finite"]) == 1.0

  # The scaled-seed gradients must match the unscaled path after unscaling.
  plain_cfg = epl.Config({"pipeline.strategy": "PreferBackward"})
  plain_state = create_train_state(model.apply, params, optax.sgd(1e-2),
                                   config=plain_cfg)
  plain_step = make_gpt_train_step(model, config=plain_cfg)
  plain_new, m2 = jax.jit(plain_step)(plain_state, {"ids": ids}, None)
  np.testing.assert_allclose(float(m["loss"]), float(m2["loss"]), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=1e-4, atol=1e-6),
      new_state.params, plain_new.params)


def test_1f1b_dropout_uses_distinct_rngs():
  """With dropout, two different seeds give different losses but the same
  seed reproduces — and the recompute inside 1F1B is self-consistent
  (finite grads, loss close to the deterministic value)."""
  mesh, pp, base, ids, params = _gpt_setup(dropout=0.2)
  grad_fn = make_gpt_1f1b_grad_fn(pp)
  f = jax.jit(lambda p, r: grad_fn(p, {"ids": ids}, r))
  (l_a, _), g_a = f(params, jax.random.PRNGKey(1))
  (l_b, _), _ = f(params, jax.random.PRNGKey(2))
  (l_a2, _), g_a2 = f(params, jax.random.PRNGKey(1))
  assert float(l_a) != float(l_b)
  np.testing.assert_allclose(float(l_a), float(l_a2), rtol=1e-6)
  finite = jax.tree_util.tree_map(
      lambda g: bool(jnp.all(jnp.isfinite(g.value
                                          if hasattr(g, "value") else g))),
      g_a)
  assert all(jax.tree_util.tree_leaves(finite))
