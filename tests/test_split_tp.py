"""Tensor-parallel op library tests (reference analog: tests/split_test.py
and the distributed dense/loss/argmax coverage)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn
from jax.sharding import PartitionSpec as P

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu import ops
from easyparallellibrary_tpu.parallel import (
    TrainState, create_sharded_train_state, make_train_step, parallelize)


class TPNet(nn.Module):
  """Two-layer MLP + large-vocab head, tensor-parallel under `split`."""
  hidden: int = 64
  vocab: int = 96
  use_split: bool = True

  @nn.compact
  def __call__(self, x):
    if self.use_split:
      with epl.split():
        h = ops.Dense(self.hidden, parallel="column")(x)
        h = nn.relu(h)
        h = ops.Dense(self.hidden, parallel="row")(h)
        h = nn.relu(h)
        logits = ops.Dense(self.vocab, parallel="column")(h)
    else:
      h = nn.relu(ops.Dense(self.hidden, parallel="none")(x))
      h = nn.relu(ops.Dense(self.hidden, parallel="none")(h))
      logits = ops.Dense(self.vocab, parallel="none")(h)
    return logits


def _data(n=32, d=16, vocab=96):
  r = np.random.RandomState(0)
  x = jnp.asarray(r.randn(n, d), jnp.float32)
  y = jnp.asarray(r.randint(0, vocab, size=(n,)), jnp.int32)
  return x, y


def _run(use_split, n_steps=5):
  epl.init()
  model = TPNet(use_split=use_split)
  if use_split:
    with epl.split():
      pass
  plan = epl.current_plan()
  mesh = plan.build_mesh()
  x, y = _data()
  tx = optax.sgd(0.1)

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, x)["params"], tx=tx)

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(7))

  def loss_fn(params, batch, rng):
    logits = model.apply({"params": params}, batch["x"])
    loss = ops.distributed_sparse_softmax_cross_entropy_with_logits(
        batch["y"], logits)
    preds = ops.distributed_argmax(logits)
    acc = jnp.mean(ops.distributed_equal(preds, batch["y"]).astype(
        jnp.float32))
    return jnp.mean(loss), {"accuracy": acc}

  step = parallelize(make_train_step(loss_fn), mesh, shardings)
  rng = jax.random.PRNGKey(3)
  losses = []
  for _ in range(n_steps):
    state, m = step(state, {"x": x, "y": y}, rng)
    losses.append(float(m["loss"]))
  return losses, state


def test_tp_kernel_is_sharded():
  _, state = _run(use_split=True, n_steps=1)
  # Find a column-parallel kernel and check its sharding spec.
  boxed = state.params["Dense_0"]["kernel"]
  from flax import linen as nn_
  assert isinstance(boxed, nn_.Partitioned)
  assert boxed.names == (None, "model")
  leaf = boxed.value
  # 8-way model axis: local shard holds 1/8 of the columns.
  assert leaf.sharding.shard_shape(leaf.shape)[1] == leaf.shape[1] // 8


@pytest.mark.quick
def test_tp_matches_unsharded():
  tp_losses, _ = _run(use_split=True)
  base_losses, _ = _run(use_split=False)
  np.testing.assert_allclose(tp_losses, base_losses, rtol=1e-4, atol=1e-5)


def test_tp_loss_decreases():
  losses, _ = _run(use_split=True, n_steps=8)
  assert losses[-1] < losses[0]


def test_sharded_ce_matches_optax():
  x = np.random.RandomState(1).randn(16, 33).astype(np.float32)
  labels = np.random.RandomState(2).randint(0, 33, size=(16,))
  ours = ops.distributed_sparse_softmax_cross_entropy_with_logits(
      jnp.asarray(labels), jnp.asarray(x))
  theirs = optax.softmax_cross_entropy_with_integer_labels(
      jnp.asarray(x), jnp.asarray(labels))
  np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-6)


def test_bf16_ce_label_grad_survives_confident_prediction():
  """The fused CE accepts bf16 logits; the label-position gradient is
  p - 1, which must be computed in fp32 *before* rounding to bf16.  If
  the softmax cotangent and the scattered -1 were each rounded to bf16
  separately, they'd cancel to exactly 0 whenever bf16(p) == 1 (any
  confidently-predicted token) — silently zeroing the training signal."""
  logits = jnp.asarray([[10.0, 0.0, 0.0, 0.0]], jnp.bfloat16)
  labels = jnp.asarray([0], jnp.int32)

  def f(lg):
    return jnp.sum(ops.distributed_sparse_softmax_cross_entropy_with_logits(
        labels, lg))

  g = jax.grad(f)(logits)
  # fp32 reference: p - 1 at the label position.
  p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)[0, 0]
  expected = float(p - 1.0)
  got = float(g[0, 0])
  assert got != 0.0, "label gradient cancelled to zero in bf16"
  np.testing.assert_allclose(got, expected, rtol=0.02)


def test_uneven_features_pad_and_match():
  """Uneven tensor-parallel dims (the reference's remainder case) are
  zero-padded to even tiles and sliced back; numerics match unsharded."""
  def run(tp):
    epl.init()
    if tp:
      with epl.split():
        pass
    mesh = epl.current_plan().build_mesh()

    class Uneven(nn.Module):
      tp: bool
      @nn.compact
      def __call__(self, x):
        if self.tp:
          with epl.split():
            h = nn.relu(ops.Dense(10, parallel="column")(x))   # 10 % 8 != 0
            return ops.Dense(6, parallel="row")(h)
        h = nn.relu(ops.Dense(10, parallel="none")(x))
        return ops.Dense(6, parallel="none")(h)

    model = Uneven(tp=tp)
    x = jnp.asarray(np.random.RandomState(0).randn(8, 4), jnp.float32)
    params = jax.jit(lambda: model.init(jax.random.PRNGKey(1), x))()["params"]
    out = jax.jit(lambda p: model.apply({"params": p}, x))(params)
    return np.asarray(out), params

  out_tp, params_tp = run(True)
  out_base, _ = run(False)
  np.testing.assert_allclose(out_tp, out_base, rtol=1e-5, atol=1e-6)
  # Column kernel padded from 10 -> 16 (8-way axis), zeros in the pad.
  k = params_tp["Dense_0"]["kernel"].value
  assert k.shape == (4, 16)
  np.testing.assert_allclose(np.asarray(k)[:, 10:], 0.0)


def test_uneven_vocab_embedding_attend():
  epl.init()
  with epl.split():
    pass
  mesh = epl.current_plan().build_mesh()

  class Tied(nn.Module):
    @nn.compact
    def __call__(self, ids):
      with epl.split():
        emb = ops.Embedding(num_embeddings=70, features=16)  # 70 % 8 != 0
        x = emb(ids)
        return emb.attend(x)

  model = Tied()
  ids = jnp.asarray([[1, 2, 69]], jnp.int32)
  params = jax.jit(lambda: model.init(jax.random.PRNGKey(0), ids))()["params"]
  logits = model.apply({"params": params}, ids)
  assert logits.shape == (1, 3, 70)  # padded rows sliced off
  table = params["Embedding_0"]["embedding"].value
  assert table.shape[0] == 72


def test_vocab_sharded_embedding():
  epl.init()
  with epl.split():
    pass
  mesh = epl.current_plan().build_mesh()

  class Emb(nn.Module):
    @nn.compact
    def __call__(self, ids):
      with epl.split():
        return ops.Embedding(num_embeddings=64, features=16)(ids)

  model = Emb()
  params = jax.jit(
      lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((2, 3), jnp.int32))
  )()["params"]
  boxed = params["Embedding_0"]["embedding"]
  assert boxed.names == ("model", None)
  out = model.apply({"params": params},
                    jnp.asarray([[1, 2], [3, 4]], jnp.int32))
  assert out.shape == (2, 2, 16)


class AutoNet(nn.Module):
  """MLP with auto-parallel Dense layers (no explicit parallel=)."""
  hidden: int = 64
  vocab: int = 96

  @nn.compact
  def __call__(self, x):
    with epl.split():
      h = nn.relu(ops.Dense(self.hidden)(x))    # Dense_0
      h = nn.relu(ops.Dense(self.hidden)(h))    # Dense_1
      return ops.Dense(self.vocab)(h)           # Dense_2


def _kernel_names(params, layer):
  return params[layer]["kernel"].names


def test_auto_tensor_split_pairs_column_row():
  """Auto tensor-split (reference TODO epl/ir/graph.py:124): auto-named
  sibling Dense layers alternate column -> row so consecutive
  projections chain through the sharded feature dim (one psum, no
  activation gather).  Opt-in via auto.tensor_split."""
  epl.init(epl.Config({"auto.tensor_split": True}))
  with epl.split():
    pass
  epl.current_plan().build_mesh()
  x, _ = _data()
  params = AutoNet().init(jax.random.PRNGKey(0), x)["params"]
  assert _kernel_names(params, "Dense_0") == (None, "model")   # column
  assert _kernel_names(params, "Dense_1") == ("model", None)   # row
  assert _kernel_names(params, "Dense_2") == (None, "model")   # column


def test_auto_tensor_split_default_off_keeps_all_column():
  epl.init()  # tensor_split defaults to False (positional pairing is opt-in)
  with epl.split():
    pass
  epl.current_plan().build_mesh()
  x, _ = _data()
  params = AutoNet().init(jax.random.PRNGKey(0), x)["params"]
  for layer in ("Dense_0", "Dense_1", "Dense_2"):
    assert _kernel_names(params, layer) == (None, "model")


def test_auto_tensor_split_matches_unsharded():
  def run(auto_pairs):
    epl.init(epl.Config({"auto.tensor_split": auto_pairs}))
    model = AutoNet()
    with epl.split():
      pass
    mesh = epl.current_plan().build_mesh()
    x, y = _data()
    tx = optax.sgd(0.1)

    def init_fn(rng):
      return TrainState.create(apply_fn=model.apply,
                               params=model.init(rng, x)["params"], tx=tx)

    state, shardings = create_sharded_train_state(
        init_fn, mesh, jax.random.PRNGKey(7))

    def loss_fn(params, batch, rng):
      logits = model.apply({"params": params}, batch["x"])
      loss = ops.distributed_sparse_softmax_cross_entropy_with_logits(
          batch["y"], logits)
      return jnp.mean(loss), {}

    step = parallelize(make_train_step(loss_fn), mesh, shardings)
    losses = []
    for _ in range(5):
      state, m = step(state, {"x": x, "y": y}, jax.random.PRNGKey(3))
      losses.append(float(m["loss"]))
    return losses

  np.testing.assert_allclose(run(True), run(False), rtol=1e-4, atol=1e-5)


def test_auto_pairing_reduces_activation_gathers():
  """The point of the pairing: the compiled forward moves fewer bytes —
  a column -> row pair needs one psum where column -> column re-gathers
  the sharded activation."""
  def compiled_text(auto_pairs):
    epl.init(epl.Config({"auto.tensor_split": auto_pairs}))
    model = AutoNet()
    with epl.split():
      pass
    mesh = epl.current_plan().build_mesh()
    x, _ = _data()

    def init_fn(rng):
      return TrainState.create(
          apply_fn=model.apply,
          params=model.init(rng, x)["params"], tx=optax.sgd(0.1))

    state, _ = create_sharded_train_state(init_fn, mesh,
                                          jax.random.PRNGKey(0))
    fwd = jax.jit(lambda p, xx: model.apply({"params": p}, xx))
    return fwd.lower(state.params, x).compile().as_text()

  paired = compiled_text(True)
  unpaired = compiled_text(False)
  assert paired.count("all-gather") < unpaired.count("all-gather")
