"""Speculative decoding: drafters, batched verification, accept/rollback.

The contracts under test (ISSUE 4 acceptance):

* greedy speculative output is BIT-EXACT vs non-speculative
  ``generate(use_cache=True)`` per request — drafting/verification is
  pure rebatching, including staggered admission on a TP=2 mesh, slot
  reuse, and stop tokens that appear mid-draft;
* sampled speculative output preserves the sampling DISTRIBUTION
  (rejection-sampling acceptance), and requests served without drafts
  keep the non-speculative engine's bitstream exactly;
* the fused speculative step compiles ONCE — draft lengths, joins and
  leaves are data, not shapes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.models.gpt import generate, slot_step_logits
from easyparallellibrary_tpu.profiler import ServingStats, percentile
from easyparallellibrary_tpu.serving import (
    ContinuousBatchingEngine, DraftModelDrafter, NgramDrafter, Request,
    allocate_kv_cache, check_draft_compatible, check_servable,
    ngram_propose, sample_token_slots, verify_tokens)

TINY = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                 d_ff=64, max_seq_len=32, dtype=jnp.float32)


def _model_and_params(cfg=TINY, seed=0):
  model = GPT(cfg)
  params = model.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, 4), jnp.int32))["params"]
  return model, params


def _prompts(lengths, vocab=64, seed=0):
  r = np.random.RandomState(seed)
  return [r.randint(0, vocab, (n,)).astype(np.int32) for n in lengths]


def _oracle(model, params, prompt, max_new):
  return np.asarray(
      generate(model, params, jnp.asarray(prompt)[None], max_new))[0]


# ---------------------------------------------------------------- exactness


@pytest.mark.slow
def test_spec_ngram_greedy_exact_staggered_slot_reuse():
  """Greedy speculation with the n-gram drafter is bit-exact vs
  generate(use_cache=True) per request — staggered admission, slot
  reuse after retirement (num_slots < num requests) — and the fused
  speculative step compiles exactly once across all of it.  (slow: six
  oracle shapes = six generate() compiles; the quick TP=2 test carries
  the staggered contract in tier-1.)"""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3, 9, 1, 6, 2))
  max_new = (6, 7, 8, 4, 5, 9)
  eng = ContinuousBatchingEngine(model, params, num_slots=3,
                                 prefill_chunk=4,
                                 drafter=NgramDrafter(k=3, ngram_max=3))
  for i in range(3):
    eng.submit(Request(uid=i, prompt=prompts[i],
                       max_new_tokens=max_new[i]))
  out = {}
  for _ in range(2):  # second wave joins a mid-flight batch
    for fin in eng.step():
      out[fin.uid] = fin.tokens
  for i in range(3, len(prompts)):
    eng.submit(Request(uid=i, prompt=prompts[i],
                       max_new_tokens=max_new[i]))
  out.update(eng.run())
  for i, p in enumerate(prompts):
    np.testing.assert_array_equal(
        out[i], _oracle(model, params, p, max_new[i]), err_msg=f"req {i}")
  # Zero recompiles: joins/leaves and varying per-slot draft lengths
  # (n-gram proposals come and go) are data, not shapes.
  assert eng._step_fn._cache_size() == 1


@pytest.mark.quick
def test_spec_tp2_greedy_exact_staggered_vs_dense():
  """ISSUE 4 acceptance: speculative greedy decoding on a TP=2 virtual
  mesh (heads-sharded slot cache) with staggered admission — plus a
  stop-token retirement — is bit-exact per request vs the dense
  single-program NON-speculative engine (itself quick-pinned to
  generate(use_cache=True) in tests/test_serving.py), with the
  speculative step compiled once."""
  import flax.linen as nn
  import optax
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state)
  epl.init(epl.Config({"cluster.mesh_shape": "data:4,model:2"}))
  mesh = epl.Env.get().cluster.build_mesh()
  cfg = GPTConfig(**{**TINY.__dict__, "tensor_parallel": True})
  model = GPT(cfg)
  prompts = _prompts((4, 7, 2, 5), seed=1)
  max_new = (6, 6, 6, 8)

  def init_fn(rng):
    return TrainState.create(
        apply_fn=model.apply,
        params=model.init(rng, jnp.asarray(prompts[0])[None])["params"],
        tx=optax.sgd(0.1))

  state, _ = create_sharded_train_state(init_fn, mesh,
                                        jax.random.PRNGKey(5))
  dense = GPT(TINY)
  host_params = jax.tree_util.tree_map(np.asarray,
                                       nn.meta.unbox(state.params))
  # Dense non-speculative oracle engine: one compiled step for every
  # request shape (vs one generate() compile per shape).
  oracle_eng = ContinuousBatchingEngine(dense, host_params, num_slots=4,
                                        prefill_chunk=4)
  for i, p in enumerate(prompts):
    oracle_eng.submit(Request(uid=i, prompt=p,
                              max_new_tokens=max_new[i]))
  ref = oracle_eng.run()
  # A stop token straight from the oracle: request 3 retires on its
  # second generated token instead of running to its budget.
  stop = int(ref[3][len(prompts[3]) + 1])

  eng = ContinuousBatchingEngine(model, state.params, mesh=mesh,
                                 num_slots=2, prefill_chunk=4,
                                 drafter=NgramDrafter(k=3, ngram_max=3))
  out = {}
  for i in range(2):
    eng.submit(Request(uid=i, prompt=prompts[i],
                       max_new_tokens=max_new[i]))
  for _ in range(2):  # requests 2/3 join a mid-flight batch
    for fin in eng.step():
      out[fin.uid] = fin.tokens
  eng.submit(Request(uid=2, prompt=prompts[2], max_new_tokens=6))
  eng.submit(Request(uid=3, prompt=prompts[3], max_new_tokens=8,
                     stop_token=stop))
  out.update(eng.run())
  for i in range(3):
    np.testing.assert_array_equal(out[i], ref[i], err_msg=f"req {i}")
  cut = list(ref[3][len(prompts[3]):]).index(stop)
  np.testing.assert_array_equal(out[3], ref[3][:len(prompts[3]) + cut + 1])
  assert eng._step_fn._cache_size() == 1


@pytest.mark.quick
def test_spec_stop_token_mid_draft_retires_exactly():
  """A stop token committed MID-DRAFT (inside an accepted burst) retires
  the request at the stop token and discards the rest of the burst —
  output equals the oracle truncated at the stop's first occurrence.
  A same-params draft model guarantees full acceptance, so the commit
  containing the stop is always a multi-token burst."""
  epl.init()
  model, params = _model_and_params(seed=3)
  (prompt,) = _prompts((5,), seed=4)
  plen = len(prompt)
  ref = _oracle(model, params, prompt, 8)
  gen = list(ref[plen:])
  stop = gen[2]                     # committed at generated index <= 2
  cut = gen.index(stop)
  eng = ContinuousBatchingEngine(
      model, params, num_slots=2, prefill_chunk=4,
      drafter=DraftModelDrafter(model, params, k=2))
  eng.submit(Request(uid="s", prompt=prompt, max_new_tokens=20,
                     stop_token=int(stop)))
  fins = []
  steps = 0
  while eng.has_work:
    fins.extend(eng.step())
    steps += 1
  assert len(fins) == 1 and fins[0].finish_reason == "stop_token"
  np.testing.assert_array_equal(fins[0].tokens, ref[:plen + cut + 1])
  # Full acceptance => the engine needed fewer steps than tokens: the
  # retiring commit really was a multi-token (mid-draft) burst.
  assert steps < 2 + cut + 1


@pytest.mark.slow
def test_spec_draft_model_full_acceptance_and_exactness():
  """A draft model sharing the target's parameters must reach 100%
  acceptance (greedy drafts == greedy target by construction) — the
  lockstep oracle for the draft-side cache mirror — while outputs stay
  bit-exact, and stats report >1 accepted tokens per drafting step.
  (slow: six oracle shapes; the quick mid-draft stop test keeps the
  same-params draft mirror burst-committing in tier-1.)"""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3, 9, 1, 6, 2))
  max_new = (6, 7, 8, 4, 5, 9)
  stats = ServingStats()
  eng = ContinuousBatchingEngine(
      model, params, num_slots=2, prefill_chunk=4,
      drafter=DraftModelDrafter(model, params, k=3), stats=stats)
  for i, p in enumerate(prompts):
    eng.submit(Request(uid=i, prompt=p, max_new_tokens=max_new[i]))
  out = eng.run()
  for i, p in enumerate(prompts):
    np.testing.assert_array_equal(
        out[i], _oracle(model, params, p, max_new[i]), err_msg=f"req {i}")
  s = stats.summary()
  assert s["acceptance_rate"] == 1.0
  assert s["accepted_per_step_mean"] > 1.0
  assert s["drafted_tokens"] == s["accepted_tokens"] > 0


@pytest.mark.slow
def test_spec_mismatched_draft_model_still_exact():
  """A draft model with DIFFERENT weights (low acceptance) cannot change
  greedy output — rejections fall back to the target's own argmax.
  (slow: the n-gram tier-1 tests already exercise heavy rejection.)"""
  epl.init()
  model, params = _model_and_params()
  draft_cfg = GPTConfig(**{**TINY.__dict__, "num_layers": 1,
                           "d_model": 16, "num_heads": 2, "d_ff": 32})
  draft_model, draft_params = _model_and_params(draft_cfg, seed=9)
  prompts = _prompts((5, 3), seed=5)
  eng = ContinuousBatchingEngine(
      model, params, num_slots=2, prefill_chunk=4,
      drafter=DraftModelDrafter(draft_model, draft_params, k=3))
  for i, p in enumerate(prompts):
    eng.submit(Request(uid=i, prompt=p, max_new_tokens=8))
  out = eng.run()
  for i, p in enumerate(prompts):
    np.testing.assert_array_equal(out[i], _oracle(model, params, p, 8),
                                  err_msg=f"req {i}")


# ----------------------------------------------------------------- sampling


def test_sampled_request_without_drafts_keeps_plain_stream():
  """A request with speculative=False on a speculative engine — and any
  slot whose drafter proposed nothing — reproduces the non-speculative
  engine's sample stream BIT-exactly (the committed-index PRNG fold is
  untouched by speculation plumbing)."""
  epl.init()
  model, params = _model_and_params()
  (prompt,) = _prompts((5,), seed=6)

  def run(drafter):
    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   prefill_chunk=4, drafter=drafter)
    eng.submit(Request(uid="s", prompt=prompt, max_new_tokens=8,
                       temperature=0.9, top_k=12, seed=7,
                       speculative=False))
    return eng.run()["s"]

  np.testing.assert_array_equal(run(None), run(NgramDrafter(k=3)))


def test_enabled_false_matches_pre_pr_stream_contract():
  """Satellite regression: with speculation disabled the engine's sample
  stream equals an INDEPENDENT replay of the documented contract —
  token i of a request is sampled from the filtered logits at its last
  committed position with fold_in(PRNGKey(seed), i) — pinning that the
  speculation plumbing changed nothing about pre-PR streams."""
  epl.init()
  model, params = _model_and_params()
  (prompt,) = _prompts((6,), seed=8)
  seed, max_new, C = 11, 5, 4
  temp = np.asarray([0.8], np.float32)
  top_k = np.asarray([10], np.int32)
  top_p = np.asarray([0.95], np.float32)

  kv, _ = allocate_kv_cache(TINY, 1, C)
  key = np.asarray(jax.random.PRNGKey(seed))
  cur, pos, last_tok = 0, 0, None
  out = []
  while len(out) < max_new:
    block = np.zeros((1, C), np.int32)
    if pos < len(prompt):
      grant = min(C, len(prompt) - pos)
      block[0, :grant] = prompt[pos:pos + grant]
      pos += grant
    else:
      block[0, 0] = last_tok
      grant = 1
    logits, kv = slot_step_logits(model, params, kv, jnp.asarray(block),
                                  jnp.asarray([cur], jnp.int32))
    cur += grant
    if pos < len(prompt):
      continue
    last = np.asarray(logits)[:, grant - 1].astype(np.float32)
    k_i = jax.vmap(jax.random.fold_in)(key[None],
                                       jnp.asarray([len(out)]))
    tok = int(np.asarray(sample_token_slots(
        jnp.asarray(last), k_i, jnp.asarray(temp), jnp.asarray(top_k),
        jnp.asarray(top_p)))[0])
    out.append(tok)
    last_tok = tok

  eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                 prefill_chunk=C, speculative=False)
  eng.submit(Request(uid="r", prompt=prompt, max_new_tokens=max_new,
                     temperature=0.8, top_k=10, top_p=0.95, seed=seed))
  got = eng.run()["r"]
  np.testing.assert_array_equal(got[len(prompt):], np.asarray(out))


def test_verify_tokens_preserves_sampling_distribution():
  """ISSUE 4 acceptance: rejection-sampling acceptance preserves the
  target distribution — over many PRNG streams the first committed
  token's empirical distribution matches the FILTERED target softmax,
  whether the (point-mass) draft is likely, unlikely, or filtered out
  entirely by top-k."""
  N, V = 6000, 8
  r = np.random.RandomState(0)
  base = (r.randn(V) * 1.5).astype(np.float32)
  tgt = jnp.broadcast_to(jnp.asarray(base), (N, 2, V)).astype(jnp.float32)
  keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(N))
  ones, zeros = jnp.ones((N,)), jnp.zeros((N,), jnp.int32)

  def emitted(draft_tok, top_k=0):
    committed, ncom, accepted = verify_tokens(
        tgt, jnp.full((N, 1), draft_tok, jnp.int32),
        jnp.ones((N,), jnp.int32), keys, zeros, ones,
        jnp.full((N,), top_k, jnp.int32), ones.astype(jnp.float32))
    return np.asarray(committed)[:, 0], np.asarray(accepted)

  def expect(top_k=0):
    x = base.copy()
    if top_k:
      x[np.argsort(x)[:-top_k]] = -np.inf
    p = np.exp(x - np.nanmax(x))
    p[~np.isfinite(p)] = 0.0
    return p / p.sum()

  for draft_tok in (int(np.argmax(base)), int(np.argmin(base))):
    first, accepted = emitted(draft_tok)
    p = expect()
    freq = np.bincount(first, minlength=V) / N
    assert 0.5 * np.abs(freq - p).sum() < 0.035
    assert abs(accepted.mean() - p[draft_tok]) < 0.035
  # Draft outside the top-k filter: never accepted, distribution still
  # matches the filtered target.
  worst = int(np.argmin(base))
  first, accepted = emitted(worst, top_k=3)
  assert accepted.sum() == 0
  p = expect(top_k=3)
  freq = np.bincount(first, minlength=V) / N
  assert 0.5 * np.abs(freq - p).sum() < 0.035


def test_verify_tokens_greedy_semantics():
  """Greedy acceptance is exact-prefix-match: drafts equal to argmax are
  kept, the first mismatch truncates and commits the argmax correction,
  a full match commits the bonus argmax."""
  V, K = 16, 3
  r = np.random.RandomState(1)
  logits = r.randn(2, K + 1, V).astype(np.float32)
  am = logits.argmax(-1)
  drafts = np.stack([am[0, :K],                       # all match
                     [am[1, 0], (am[1, 1] + 1) % V, am[1, 2]]])  # miss @1
  keys = np.stack([np.asarray(jax.random.PRNGKey(i)) for i in range(2)])
  committed, ncom, accepted = verify_tokens(
      jnp.asarray(logits), jnp.asarray(drafts, jnp.int32),
      jnp.full((2,), K, jnp.int32), jnp.asarray(keys),
      jnp.zeros((2,), jnp.int32), jnp.zeros((2,)),
      jnp.zeros((2,), jnp.int32), jnp.ones((2,)))
  committed, ncom, accepted = (np.asarray(committed), np.asarray(ncom),
                               np.asarray(accepted))
  assert list(accepted) == [K, 1] and list(ncom) == [K + 1, 2]
  np.testing.assert_array_equal(committed[0], am[0])        # + bonus
  np.testing.assert_array_equal(committed[1][:2], am[1][:2])  # correction


# ----------------------------------------------------------------- drafters


def test_ngram_propose_lookup_semantics():
  h = np.asarray([1, 2, 3, 9, 9, 1, 2, 3, 7, 7, 1, 2, 3], np.int32)
  # Suffix [1,2,3]: most recent earlier occurrence ends at index 7 ->
  # continuation [7, 7, 1, ...], capped at k.
  np.testing.assert_array_equal(ngram_propose(h, 3, 3, 1), [7, 7, 1])
  np.testing.assert_array_equal(ngram_propose(h, 2, 3, 1), [7, 7])
  # No match at any n in [min, max] -> empty proposal.
  assert ngram_propose(np.asarray([1, 2, 3, 4]), 3, 3, 2).size == 0
  # ngram_min=1 falls back to the last unigram's continuation.
  np.testing.assert_array_equal(
      ngram_propose(np.asarray([5, 8, 5, 9, 5]), 2, 3, 1), [9, 5])
  # Degenerate short history never crashes.
  assert ngram_propose(np.asarray([4]), 3, 3, 1).size == 0


def test_scheduler_draft_cap_budget_and_opt_out():
  """draft_cap = min(k, remaining-1) for speculation-eligible decode
  slots; prefilling slots and opted-out requests get 0."""
  from easyparallellibrary_tpu.serving import FCFSScheduler
  sched = FCFSScheduler(num_slots=3, prefill_chunk=4, max_seq_len=64,
                        spec_k=3)
  sched.submit(Request(uid="a", prompt=np.arange(2, dtype=np.int32),
                       max_new_tokens=10))
  sched.submit(Request(uid="b", prompt=np.arange(2, dtype=np.int32),
                       max_new_tokens=10, speculative=False))
  sched.submit(Request(uid="c", prompt=np.arange(8, dtype=np.int32),
                       max_new_tokens=3))
  plan = sched.plan_step()
  assert list(plan.draft_cap) == [0, 0, 0]   # everyone still prefilling
  sched.commit(np.zeros(3, np.int32))
  plan = sched.plan_step()
  # a: decoding, remaining 9 -> cap 3; b: opted out; c: still prefilling.
  assert list(plan.draft_cap) == [3, 0, 0]
  assert set(sched.slot_histories(plan)) == {0}
  sched.commit(np.zeros(3, np.int32))
  plan = sched.plan_step()
  # c finished prefill last step: 1 committed, remaining 2 -> cap 1.
  assert plan.draft_cap[2] == 1
  # Multi-token commit: a commits 3 at once (2 accepted + bonus).
  toks = np.zeros((3, 4), np.int32)
  toks[0] = [41, 42, 43, 44]
  sched.commit(toks, np.asarray([3, 1, 1]))
  assert sched.active[0].generated[-3:] == [41, 42, 43]


# ------------------------------------------------------------- capabilities


def test_capability_guards_are_actionable():
  epl.init()
  pp = GPTConfig(**{**TINY.__dict__, "pipeline_stages": 2})
  with pytest.raises(ValueError, match="pipeline.*ROADMAP"):
    check_servable(pp)
  moe = GPTConfig(**{**TINY.__dict__, "num_experts": 2})
  with pytest.raises(ValueError, match="MoE.*ROADMAP"):
    check_servable(moe)
  # The engine rejects through the same guard (message parity with PR 3).
  model_pp = GPT(pp)
  with pytest.raises(ValueError, match="pipeline"):
    ContinuousBatchingEngine(model_pp, {}, num_slots=1)
  # Draft-model shape guards.
  other_vocab = GPTConfig(**{**TINY.__dict__, "vocab_size": 32})
  with pytest.raises(ValueError, match="vocab_size.*token ids"):
    check_draft_compatible(TINY, other_vocab)
  short = GPTConfig(**{**TINY.__dict__, "max_seq_len": 16})
  with pytest.raises(ValueError, match="max_seq_len"):
    check_draft_compatible(TINY, short)
  with pytest.raises(ValueError, match="pipeline"):
    check_draft_compatible(TINY, pp)
  # And end-to-end: binding an incompatible draft model fails the same way.
  model, params = _model_and_params()
  bad_model, bad_params = _model_and_params(other_vocab)
  with pytest.raises(ValueError, match="vocab_size"):
    ContinuousBatchingEngine(
        model, params, num_slots=1, prefill_chunk=4,
        drafter=DraftModelDrafter(bad_model, bad_params, k=2))
  # k must fit the fused step's chunk.
  with pytest.raises(ValueError, match="prefill_chunk >= k"):
    ContinuousBatchingEngine(model, params, num_slots=1, prefill_chunk=4,
                             drafter=NgramDrafter(k=4))
  # draft_model kind needs weights.
  with pytest.raises(ValueError, match="draft_model"):
    ContinuousBatchingEngine(
        model, params, num_slots=1, prefill_chunk=8,
        config=epl.Config({"serving.speculative.enabled": True,
                           "serving.speculative.kind": "draft_model"}))


def test_speculative_config_group_validation():
  conf = epl.Config({"serving.speculative.enabled": True,
                     "serving.speculative.k": 2,
                     "serving": {"speculative": {"ngram_max": 5}}})
  spec = conf.serving.speculative
  assert spec.enabled and spec.k == 2 and spec.ngram_max == 5
  conf.serving.speculative.k = 3          # writable through the view
  assert conf.serving.speculative.k == 3
  with pytest.raises(ValueError, match="speculative.k"):
    epl.Config({"serving.speculative.k": 0})
  with pytest.raises(ValueError, match="kind"):
    epl.Config({"serving.speculative.kind": "psychic"})
  with pytest.raises(ValueError, match="ngram_min"):
    epl.Config({"serving.speculative.ngram_min": 4,
                "serving.speculative.ngram_max": 2})
  with pytest.raises(ValueError, match="prefill_chunk"):
    epl.Config({"serving.speculative.enabled": True,
                "serving.speculative.k": 4,
                "serving.prefill_chunk": 4})
  # Disabled k=4 with chunk 4 is fine (nothing will draft).
  epl.Config({"serving.speculative.k": 4, "serving.prefill_chunk": 4})


def test_speculative_env_var_override(monkeypatch):
  monkeypatch.setenv("EPL_SERVING_SPECULATIVE_K", "6")
  assert epl.Config().serving.speculative.k == 6


def test_config_enabled_engine_uses_ngram_drafter():
  """serving.speculative.* alone (no explicit drafter object) turns the
  engine speculative: the configured n-gram drafter is resolved and the
  scheduler budgets drafts for it.  (Exactness of the resulting engine
  is pinned by the quick tests; this one checks only the config
  plumbing, host-side.)"""
  epl.init(epl.Config({"serving.speculative.enabled": True,
                       "serving.speculative.k": 3,
                       "serving.speculative.ngram_max": 2,
                       "serving.prefill_chunk": 4,
                       "serving.num_slots": 2}))
  model, params = _model_and_params()
  eng = ContinuousBatchingEngine(model, params)
  assert isinstance(eng.drafter, NgramDrafter)
  assert eng.drafter.k == 3 and eng.drafter.ngram_max == 2
  assert eng.scheduler.spec_k == 3
  # An engine-kwarg override beats the config group.
  eng_off = ContinuousBatchingEngine(model, params, speculative=False)
  assert eng_off.drafter is None and eng_off.scheduler.spec_k == 0
  # ...and beats even an explicit drafter object: the opt-out must be
  # trustworthy (it guards sampled requests' bitstreams).
  eng_off2 = ContinuousBatchingEngine(model, params, speculative=False,
                                      drafter=NgramDrafter(k=3))
  assert eng_off2.drafter is None


# ------------------------------------------------------------------ metrics


def test_serving_stats_speculation_counters_degrade_gracefully():
  """Satellite: acceptance-rate rollups over 0- and 1-sample windows —
  legitimately empty early in a run — degrade to 0.0 / the lone sample
  instead of raising, and percentile() clamps out-of-range q."""
  stats = ServingStats(clock=lambda: 0.0)
  s = stats.summary()                      # 0 samples everywhere
  assert s["acceptance_rate"] == 0.0
  assert s["accepted_per_step_p50"] == 0.0 == s["accepted_per_step_p99"]
  stats.note_step(active_slots=1, num_slots=2, prefill_tokens=4,
                  decode_tokens=0, step_time_s=0.1)   # prefill: no drafts
  assert stats.summary()["accepted_per_step_p50"] == 0.0
  stats.note_step(active_slots=1, num_slots=2, prefill_tokens=0,
                  decode_tokens=1, step_time_s=0.1, drafted_tokens=3,
                  accepted_tokens=2)                   # 1-sample window
  s = stats.summary()
  assert s["drafted_tokens"] == 3 and s["accepted_tokens"] == 2
  assert s["acceptance_rate"] == pytest.approx(2 / 3)
  assert s["accepted_per_step_p50"] == 2.0 == s["accepted_per_step_p99"]
  assert s["accepted_per_step_mean"] == 2.0
  assert percentile([], 50) == 0.0
  assert percentile([4.0], 0) == 4.0 == percentile([4.0], 100)
  assert percentile([1.0, 2.0], 150) == 2.0    # clamped, not IndexError
  assert percentile([1.0, 2.0], -5) == 1.0


# ------------------------------------------------------------- restore path


def test_draft_model_from_checkpoint_and_shape_peek(tmp_path):
  """Satellite: the draft-model restore path rides saver.restore_params
  (checksum-validated fallback chain) and validates the checkpoint's
  embedding shape from the index BEFORE loading shards."""
  from easyparallellibrary_tpu.runtime.saver import (
      peek_leaf_shapes, save_checkpoint)
  epl.init()
  model, params = _model_and_params(seed=12)
  root = str(tmp_path / "draft_ckpt")
  save_checkpoint(root, params, step=7)

  shapes, step = peek_leaf_shapes(root)
  assert step == 7
  assert shapes["wte/embedding"] == (TINY.vocab_size, TINY.d_model)

  drafter = DraftModelDrafter.from_checkpoint(root, model, k=2)
  eng = ContinuousBatchingEngine(model, params, num_slots=1,
                                 prefill_chunk=4, drafter=drafter)
  (prompt,) = _prompts((4,), seed=13)
  eng.submit(Request(uid=0, prompt=prompt, max_new_tokens=3))
  out = eng.run()
  np.testing.assert_array_equal(out[0], _oracle(model, params, prompt, 3))

  # Wrong-vocabulary draft config fails from the index alone.
  wrong = GPT(GPTConfig(**{**TINY.__dict__, "vocab_size": 32}))
  with pytest.raises(ValueError, match="vocab-64.*vocab_size=32"):
    DraftModelDrafter.from_checkpoint(root, wrong, k=2)
  with pytest.raises(FileNotFoundError):
    peek_leaf_shapes(str(tmp_path / "nonexistent"))
