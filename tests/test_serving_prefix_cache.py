"""Fleet-wide copy-on-write prefix caching (ISSUE 16).

The exactness contract under test: warm admission is a pure PLANNING
change — matched blocks map into the block table by reference and the
prompt cursor jumps past them, but every token the engine emits is
bit-identical to a cold prefill of the same prompt, no matter how much
of the prompt came out of the radix tree, when the sharer was admitted,
or whether cached blocks were evicted mid-flight to refill the pool.
Compile count stays 1 across hit/miss/evict (block tables are data).
The host-side tree is pure numpy/zlib, so its refcount and LRU
invariants are pinned at unit level with a fake clock; the engine-level
tests pin the end-to-end streams against ``generate(use_cache=True)``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.models.gpt import generate
from easyparallellibrary_tpu.serving import (
    BlockAllocator, ContinuousBatchingEngine, PrefixCache, Request,
    block_prefix_keys)
from easyparallellibrary_tpu.testing import chaos

TINY = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                 d_ff=64, max_seq_len=32, dtype=jnp.float32)


def _model_and_params(cfg=TINY, seed=0):
  model = GPT(cfg)
  params = model.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, 4), jnp.int32))["params"]
  return model, params


def _oracle(model, params, prompt, max_new):
  return np.asarray(
      generate(model, params, jnp.asarray(prompt)[None], max_new))[0]


def _warm_engine(model, params, **kw):
  kw.setdefault("num_slots", 2)
  kw.setdefault("prefill_chunk", 4)
  kw.setdefault("paged", True)
  kw.setdefault("block_size", 4)
  kw.setdefault("prefix_cache", True)
  return ContinuousBatchingEngine(model, params, **kw)


# ------------------------------------------------------------- unit: keys


def test_block_prefix_keys_block_aligned_and_chained():
  """Router affinity keys are per-full-block chained digests: a shared
  leading block yields a shared depth-1 key even when the prompts
  diverge later, keys only extend with COMPLETE extra blocks, and the
  short-prompt fallback hashes the whole prompt under a distinct salt
  (a 1-block prompt and its 4-token prefix must not collide)."""
  a = np.arange(1, 13, dtype=np.int32)           # 12 tokens, 3 blocks
  b = np.concatenate([a[:8], a[8:] + 7])         # diverges in block 2
  ka, kb = block_prefix_keys(a, 4), block_prefix_keys(b, 4)
  # Full blocks strictly before the last token: (12-1)//4 = 2 depths.
  assert len(ka) == len(kb) == 2
  assert ka[0] == kb[0] and ka[1] == kb[1]
  c = np.concatenate([a[:4], a[4:8] + 7, a[8:]])  # diverges in block 1
  kc = block_prefix_keys(c, 4)
  assert kc[0] == ka[0] and kc[1] != ka[1]
  # Chaining: depth-2 key depends on depth-1 content, not just block 2.
  assert block_prefix_keys(np.concatenate([c[:8], a[8:]]), 4)[1] != ka[1]
  # max_blocks caps the walk.
  assert block_prefix_keys(a, 4, max_blocks=1) == ka[:1]
  # Prompts covering no full block (strictly before their last token)
  # fall back to a whole-prompt key under a distinct salt: a 4-token
  # prompt must not collide with the depth-1 digest of those 4 tokens.
  short = block_prefix_keys(a[:4], 4)
  assert len(short) == 1 and short[0] != ka[0]
  assert block_prefix_keys(a[:3], 4) != short


# -------------------------------------------- unit: refcounts + eviction


def test_radix_refcount_and_eviction_invariants():
  """Tree entries hold their own refcount: a registered block survives
  its owner's release, a matched block survives tree eviction, and
  ``evict_for_space`` only ever frees leaves nobody maps (refcount 1),
  parents strictly after their children."""
  alloc = BlockAllocator(num_blocks=16, block_size=4)
  cache = PrefixCache(alloc, block_size=4)
  toks = np.arange(1, 13, dtype=np.int32)
  owned = [alloc.alloc() for _ in range(3)]
  assert cache.register(toks, 3, owned) == 3
  assert cache.num_cached_blocks == 3
  for b in owned:
    assert alloc.refcount(b) == 2       # owner + tree
  # Owner retires: blocks now pinned by the tree alone.
  for b in owned:
    alloc.decref(b)
  assert all(alloc.refcount(b) == 1 for b in owned)
  # A sharer matches the first two blocks (strictly before the last
  # prefix token: (12-1)//4 = 2) and increfs them.
  matched = cache.match(toks)
  assert matched == owned[:2]
  assert cache.hits == 1 and cache.blocks_reused == 2
  assert [alloc.refcount(b) for b in owned] == [2, 2, 1]
  # Eviction sweep: only the unmapped leaf (owned[2]) is reclaimable —
  # owned[:2] are mapped (refcount 2) and owned[0] is an inner node.
  assert cache.evict_for_space(need=3) == 1
  assert cache.num_cached_blocks == 2
  assert alloc.refcount(owned[2]) == 0          # returned to the pool
  assert [alloc.refcount(b) for b in owned[:2]] == [2, 2]
  # The sharer's mapping is untouched by eviction; releasing it makes
  # the remaining chain evictable deepest-first in ONE sweep (a parent
  # freed of its last child is re-touched newer, visited later).
  for b in matched:
    alloc.decref(b)
  assert cache.evict_for_space(need=8) == 2
  assert cache.num_cached_blocks == 0
  assert alloc.num_free == 15                   # all but NULL_BLOCK


def test_radix_register_dedup_and_budget():
  """Registering the same content twice keeps the FIRST physical block
  (the duplicate owner keeps its copy unshared), and ``max_cached_blocks``
  sheds LRU-front leaves even while mapped — the budget bounds the
  TREE's pin count, not sharers' mappings."""
  alloc = BlockAllocator(num_blocks=16, block_size=4)
  cache = PrefixCache(alloc, block_size=4, max_cached_blocks=2)
  toks = np.arange(1, 13, dtype=np.int32)
  first = [alloc.alloc() for _ in range(2)]
  cache.register(toks, 2, first)
  dup = [alloc.alloc() for _ in range(2)]
  cache.register(toks, 2, dup)
  # Existing nodes win: no extra pin on the duplicates.
  assert cache.num_cached_blocks == 2
  assert all(alloc.refcount(b) == 2 for b in first)
  assert all(alloc.refcount(b) == 1 for b in dup)
  # A third distinct chain overflows the budget: the oldest leaf goes.
  other = np.arange(40, 52, dtype=np.int32)
  blks = [alloc.alloc() for _ in range(2)]
  before = cache.evictions
  cache.register(other, 2, blks)
  assert cache.num_cached_blocks == 2
  assert cache.evictions > before


def test_session_ttl_expiry_fake_clock():
  """TTL expiry pops stale entries from the LRU front only: a re-matched
  (touched) chain survives the sweep that reclaims an untouched one, and
  expired blocks return to the pool."""
  now = [0.0]
  alloc = BlockAllocator(num_blocks=16, block_size=4)
  cache = PrefixCache(alloc, block_size=4, session_ttl_s=10.0,
                      clock=lambda: now[0])
  a = np.arange(1, 13, dtype=np.int32)
  b = np.arange(40, 52, dtype=np.int32)
  for toks in (a, b):
    blks = [alloc.alloc() for _ in range(2)]
    cache.register(toks, 2, blks)
    for blk in blks:
      alloc.decref(blk)                  # session-retired: tree-only
  assert cache.num_cached_blocks == 4
  now[0] = 8.0
  for blk in cache.match(a):             # refresh chain A...
    alloc.decref(blk)
  assert cache.expire() == 0             # ...nothing stale yet
  now[0] = 12.0                          # B untouched since t=0
  assert cache.expire() == 2
  assert cache.num_cached_blocks == 2
  assert cache.match(b) == []
  survivors = cache.match(a)
  assert survivors
  for blk in survivors:
    alloc.decref(blk)
  now[0] = 25.0
  assert cache.expire() == 2
  assert alloc.num_free == 15


# --------------------------------------------------- engine: bit-exactness


@pytest.mark.quick
def test_warm_admission_bit_exact_with_cow_divergence():
  """Session reuse end to end: requests served one after another share
  prompt prefixes through the radix tree — including one that diverges
  MID-block and one that forks right after the shared blocks — and every
  warm stream matches its from-scratch oracle bit-exactly.  Hit/reuse
  counters advance, the tree never double-frees (all non-pinned blocks
  return to the pool), and the fused step compiles once."""
  epl.init()
  model, params = _model_and_params()
  base = np.arange(1, 9, dtype=np.int32)           # 2 full shared blocks
  prompts = [
      np.concatenate([base, [9]]),                 # seeds the tree
      np.concatenate([base, [10, 11]]),            # forks after block 2
      np.concatenate([base[:6], [12, 13, 14]]),    # diverges inside blk 2
  ]
  eng = _warm_engine(model, params, num_slots=4)
  out = {}
  for i, p in enumerate(prompts):
    eng.submit(Request(uid=i, prompt=np.asarray(p, np.int32),
                       max_new_tokens=6))
    out.update(eng.run())                          # sequential sessions
  assert eng._step_fn._cache_size() == 1
  for i, p in enumerate(prompts):
    np.testing.assert_array_equal(out[i], _oracle(model, params, p, 6),
                                  err_msg=f"req {i}")
  s = eng.scheduler
  assert s.prefix_hits == 2 and s.prefix_misses == 1
  # r1 reuses both shared blocks; r2 only the first (divergence lands
  # inside the second block, which COW rebuilds fresh).
  assert s.prefix_blocks_reused == 3
  # Live slots all retired: every block still held is a tree pin.
  assert s.kv_blocks_used == s.prefix_cached_blocks > 0


@pytest.mark.quick
def test_cow_shares_physical_blocks_never_writes_through():
  """The sharing is real: a warm request's leading table entries are the
  SAME physical blocks its predecessor wrote (refcount counts both the
  tree and the live mapping), and after the sharer decodes past the
  shared region its divergent tail lands in fresh blocks — re-matching
  the original prefix still returns the original content."""
  epl.init()
  model, params = _model_and_params(seed=2)
  base = np.arange(1, 9, dtype=np.int32)
  eng = _warm_engine(model, params)
  eng.submit(Request(uid="seed", prompt=np.concatenate([base, [9]]),
                     max_new_tokens=4))
  out = eng.run()
  tree_blocks = list(eng.scheduler.prefix_cache.match(
      np.concatenate([base, [9]])))
  for b in tree_blocks:
    eng.scheduler.block_allocator.decref(b)        # probe only
  assert len(tree_blocks) == 2
  eng.submit(Request(uid="fork", prompt=np.concatenate([base, [10, 11]]),
                     max_new_tokens=6))
  eng.step()
  slot = next(iter(eng.scheduler.active))
  mapped = eng.scheduler.slot_blocks(slot)
  assert mapped[:2] == tree_blocks                 # physical overlap
  for b in tree_blocks:                            # tree + live sharer
    assert eng.scheduler.block_allocator.refcount(b) >= 2
  out.update(eng.run())
  np.testing.assert_array_equal(
      out["fork"],
      _oracle(model, params, np.concatenate([base, [10, 11]]), 6))
  # Shared content untouched by the fork's decode: a third request over
  # the ORIGINAL prompt still reproduces its oracle through the tree.
  eng.submit(Request(uid="again", prompt=np.concatenate([base, [9]]),
                     max_new_tokens=4))
  out.update(eng.run())
  np.testing.assert_array_equal(out["again"], out["seed"])


@pytest.mark.quick
def test_fault_free_guard_unique_prompts_identical_to_baseline():
  """Cache ON with nothing shareable is a no-op: unique prompts produce
  the identical stream a cache-off engine produces, hits stay 0, and
  the fused step still compiles exactly once."""
  epl.init()
  model, params = _model_and_params(seed=3)
  r = np.random.RandomState(11)
  prompts = [r.randint(0, 64, (n,)).astype(np.int32)
             for n in (5, 9, 3, 7)]

  def drive(prefix_cache):
    eng = _warm_engine(model, params, num_slots=2,
                       prefix_cache=prefix_cache)
    for i, p in enumerate(prompts):
      eng.submit(Request(uid=i, prompt=p, max_new_tokens=5))
    out = eng.run(max_steps=300)
    assert eng._step_fn._cache_size() == 1
    return eng, out

  warm_eng, warm = drive(True)
  _, cold = drive(False)
  for i in range(len(prompts)):
    np.testing.assert_array_equal(warm[i], cold[i], err_msg=f"req {i}")
  assert warm_eng.scheduler.prefix_hits == 0
  assert warm_eng.scheduler.prefix_misses == len(prompts)


@pytest.mark.quick
def test_warm_tp2_staggered_admission_bit_exact():
  """Warm admission composes with TP=2 sharded serving and mid-flight
  joins: a sharer admitted into a RUNNING batch maps the retiree's
  blocks by reference and still matches the single-device oracle."""
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state)
  import optax
  epl.init(epl.Config({"cluster.mesh_shape": "data:4,model:2"}))
  mesh = epl.Env.get().cluster.build_mesh()
  cfg = GPTConfig(**{**TINY.__dict__, "tensor_parallel": True})
  model = GPT(cfg)
  base = np.arange(1, 9, dtype=np.int32)
  prompts = [np.concatenate([base, [9]]).astype(np.int32),
             np.concatenate([base, [10, 11]]).astype(np.int32),
             np.arange(20, 27, dtype=np.int32)]

  def init_fn(rng):
    return TrainState.create(
        apply_fn=model.apply,
        params=model.init(rng, jnp.asarray(prompts[0])[None])["params"],
        tx=optax.sgd(0.1))

  state, _ = create_sharded_train_state(init_fn, mesh,
                                        jax.random.PRNGKey(5))

  def drive(prefix_cache):
    eng = ContinuousBatchingEngine(model, state.params, mesh=mesh,
                                   num_slots=2, prefill_chunk=4,
                                   paged=True, block_size=4,
                                   prefix_cache=prefix_cache)
    eng.submit(Request(uid=0, prompt=prompts[0], max_new_tokens=5))
    out = eng.run()                                # seeds the tree
    eng.submit(Request(uid=2, prompt=prompts[2], max_new_tokens=6))
    for fin in eng.step():                         # unrelated req running
      out[fin.uid] = fin.tokens
    eng.submit(Request(uid=1, prompt=prompts[1], max_new_tokens=5))
    out.update(eng.run())                          # warm join mid-flight
    assert eng._step_fn._cache_size() == 1
    return eng, out

  warm_eng, warm = drive(True)
  assert warm_eng.scheduler.prefix_hits >= 1
  _, cold = drive(False)
  for i in range(len(prompts)):
    np.testing.assert_array_equal(warm[i], cold[i], err_msg=f"req {i}")


# ------------------------------------------- engine: eviction + requeue


@pytest.mark.quick
def test_cached_blocks_evicted_before_any_preemption():
  """Pool pressure reclaims session-cached (tree-only) blocks BEFORE
  preempting any live slot: a pool sized so the second request cannot
  prefill alongside the first one's retired session serves both without
  a single preemption, and the evicted-session request still replays
  its prompt cold bit-exactly."""
  epl.init()
  model, params = _model_and_params(seed=4)
  r = np.random.RandomState(5)
  p1 = r.randint(0, 64, (12,)).astype(np.int32)
  p2 = r.randint(0, 64, (12,)).astype(np.int32)
  # 8 usable blocks (minimum legal pool); each request needs
  # ceil(22/4) = 6 blocks for prompt+generation, and the first leaves 5
  # session blocks cached — the second CANNOT prefill its tail without
  # reclaiming them from the tree.
  eng = _warm_engine(model, params, num_slots=2, num_blocks=9)
  eng.submit(Request(uid="a", prompt=p1, max_new_tokens=10))
  out = eng.run(max_steps=300)
  cached = eng.scheduler.prefix_cached_blocks
  assert cached > 0
  eng.submit(Request(uid="b", prompt=p2, max_new_tokens=10))
  out.update(eng.run(max_steps=300))
  assert eng.scheduler.preemptions == 0
  assert eng.scheduler.prefix_evictions > 0
  assert eng._step_fn._cache_size() == 1
  for uid, p in (("a", p1), ("b", p2)):
    np.testing.assert_array_equal(out[uid], _oracle(model, params, p, 10),
                                  err_msg=uid)


@pytest.mark.quick
def test_requeue_rematches_own_prefix_and_releases_refs():
  """A quarantined request's registered blocks stay pinned by the tree
  across its requeue, so re-admission warm-matches its OWN committed
  prefix (near-instant replay) — and the replayed stream is still the
  oracle's.  The end state leaks nothing: every live refcount is a
  tree pin."""
  epl.init()
  model, params = _model_and_params()
  p = np.arange(1, 10, dtype=np.int32)
  eng = _warm_engine(model, params, resilience=True)
  inj = chaos.NaNLogitsInjector(eng, bad_calls=(2, 3))
  eng.submit(Request(uid="q", prompt=p, max_new_tokens=6))
  out = eng.run()
  assert inj.poisoned == [2, 3]
  assert eng.stats.requeues == 1
  assert eng._step_fn._cache_size() == 1
  # The replay admission hit the tree (its own commit-gated blocks).
  assert eng.scheduler.prefix_hits >= 1
  assert eng.finished["q"].finish_reason == "length"
  np.testing.assert_array_equal(out["q"], _oracle(model, params, p, 6))
  assert (eng.scheduler.kv_blocks_used
          == eng.scheduler.prefix_cached_blocks)


@pytest.mark.quick
def test_evacuation_releases_tree_refs_clean():
  """Evacuating a warm engine (failover migration) releases slot refs
  while tree pins survive; clearing the cache afterwards returns every
  block to the pool — no refcount is stranded by the migration."""
  epl.init()
  model, params = _model_and_params()
  base = np.arange(1, 9, dtype=np.int32)
  eng = _warm_engine(model, params)
  eng.submit(Request(uid="s", prompt=np.concatenate([base, [9]]),
                     max_new_tokens=4))
  eng.run()
  eng.submit(Request(uid="w", prompt=np.concatenate([base, [10, 11]]),
                     max_new_tokens=8))
  eng.step()                         # warm request mid-flight
  assert eng.scheduler.prefix_hits == 1
  snaps = eng.scheduler.evacuate()
  assert [s["request"]["uid"] for s in snaps] == ["w"]
  # Slot mappings gone; only tree pins remain.
  assert eng.scheduler.kv_blocks_used == eng.scheduler.prefix_cached_blocks
  assert eng.scheduler.prefix_cache.clear() > 0
  assert eng.scheduler.kv_blocks_used == 0


def test_prefix_cache_requires_paged():
  """Config validation and the scheduler both reject prefix caching on
  the contiguous engine — sharing is block-granular by construction."""
  with pytest.raises(ValueError, match="paged"):
    epl.Config({"serving.prefix_cache.enabled": True})
  from easyparallellibrary_tpu.serving.scheduler import FCFSScheduler
  with pytest.raises(ValueError, match="paged"):
    FCFSScheduler(num_slots=2, prefill_chunk=4, max_seq_len=32,
                  prefix_cache=True)
