"""Unified tracing & telemetry (ISSUE 5): span tracer, Perfetto export,
per-request serving timelines, one metric schema.

The acceptance contract: a staggered-admission serving run plus a short
``fit()`` with tracing enabled yield (a) Perfetto-loadable JSON that
passes the schema validator (required keys, monotonic ts, paired B/E),
(b) one complete lifecycle track per request — admit/prefill/decode/
retire spans, speculation accepted-count events when drafting — and
(c) no observability tax: zero change in jit cache size, no added
per-step host syncs (the tracer runs under a device-to-host transfer
guard), and traced step time within 5% of untraced on the CPU mesh.

One module-scoped traced run (fit + speculative serving + interleaved
on/off timing episodes) feeds the acceptance assertions so the compile
budget is paid once.
"""

import json
import statistics
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu import ops
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.observability import (
    MetricRegistry, validate_trace)
from easyparallellibrary_tpu.observability import report, trace as trace_lib
from easyparallellibrary_tpu.parallel import (
    TrainState, create_sharded_train_state, make_train_step, parallelize)
from easyparallellibrary_tpu.profiler import ServingStats
from easyparallellibrary_tpu.profiler.flops import FlopsProfiler
from easyparallellibrary_tpu.runtime.loop import fit
from easyparallellibrary_tpu.serving import (
    ContinuousBatchingEngine, DraftModelDrafter, Request)
from easyparallellibrary_tpu.utils.metrics_writer import MetricsWriter

TINY = GPTConfig(vocab_size=64, num_layers=1, num_heads=4, d_model=32,
                 d_ff=64, max_seq_len=32, dtype=jnp.float32)


class Net(nn.Module):
  @nn.compact
  def __call__(self, x):
    return ops.Dense(1, parallel="none")(jnp.tanh(
        ops.Dense(8, parallel="none")(x)))


@pytest.fixture(scope="module", autouse=True)
def _drop_ambient_tracer():
  """The ambient tracer outlives the per-test Env reset; drop it after
  this module so later test files run untraced."""
  yield
  trace_lib.reset()


@pytest.fixture(scope="module")
def traced_run(tmp_path_factory):
  """One traced staggered speculative serving episode + interleaved
  tracer-on/off timing episodes on the SAME compiled engine, then a
  short traced fit().  Everything the acceptance tests assert on is
  produced here, so the jit compile budget is paid once for the module.

  Serving runs BEFORE fit on purpose: running fit first makes the
  engine's fused step recompile once on its second call — a
  pre-existing fit/engine interplay present on the seed tree and
  independent of tracing (verified by replaying this sequence on the
  pre-PR tree; ROADMAP notes it) — which would confound the zero-
  recompile and overhead measurements below.
  """
  work = tmp_path_factory.mktemp("obs")
  ckpt = str(work / "ck")
  trace_path = str(work / "trace.json")
  epl.init(epl.Config({"observability": {"enabled": True}}))
  tracer = trace_lib.ensure_configured()

  # ---- serving: staggered admission, same-params draft model ----------
  # (a drafter sharing the target's params always proposes and always
  # gets accepted under greedy — guaranteed `speculate` spans with
  # accepted counts, the acceptance criterion's "when drafting").
  gpt = GPT(TINY)
  params = gpt.init(jax.random.PRNGKey(0),
                    jnp.zeros((1, 4), jnp.int32))["params"]
  eng = ContinuousBatchingEngine(
      gpt, params, num_slots=2, prefill_chunk=4,
      drafter=DraftModelDrafter(gpt, params, k=2), stats=ServingStats())
  rp = np.random.RandomState(1)
  prompts = [rp.randint(0, 64, (n,)).astype(np.int32)
             for n in (5, 3, 6, 2)]

  def submit(i):
    eng.submit(Request(uid=f"req{i}", prompt=prompts[i],
                       max_new_tokens=5 + i))

  outputs = {}
  submit(0), submit(1)
  for _ in range(2):           # the second wave joins mid-flight
    for fin in eng.step():
      outputs[fin.uid] = fin.tokens
  submit(2), submit(3)
  outputs.update(eng.run())
  engine_step_cache = eng._step_fn._cache_size()

  # ---- overhead guard: interleaved on/off episodes, same engine -------
  # The engine is compiled and warm; each episode re-serves the same
  # request mix, alternating the tracer switch, so both sides run the
  # identical step sequence.  The toggle only flips BETWEEN episodes
  # (each drains its queue), so recorded lifecycles stay B/E-balanced.
  # Per-STEP durations are collected: the acceptance compares minimum
  # achievable step time, which ~70 samples per side pin tightly while
  # episode-level wall clock stays hostage to the shared box.
  def episode():
    import time
    for i in range(4):
      submit(i)
    steps = []
    while eng.has_work:
      t0 = time.perf_counter()
      eng.step()
      steps.append(time.perf_counter() - t0)
    return steps

  episode()                    # warm the slot-reuse paths either side
  times = {True: [], False: []}
  # GC held off during the measurement: traced episodes allocate ring
  # events that SURVIVE the episode, so collection pauses (tens of ms in
  # an object-heavy pytest process) land disproportionately on the
  # traced side and would measure the collector, not the tracer.
  import gc
  gc.collect()
  gc.disable()
  try:
    # ABBA order: a monotone warm-up or load trend lands equally on
    # both sides (a plain alternation hands every colder slot to one
    # side, which a min-compare amplifies).
    for on in [True, False, False, True] * 4:
      tracer.enabled = on
      times[on].extend(episode())
  finally:
    gc.enable()
  tracer.enabled = True
  engine_step_cache_after = eng._step_fn._cache_size()

  # ---- short fit(): phase spans, checkpoint spans, auto JSONL sink ----
  mesh = epl.current_plan().build_mesh()
  model = Net()
  r = np.random.RandomState(0)
  batch = {"x": jnp.asarray(r.randn(16, 4), jnp.float32),
           "y": jnp.asarray(r.randn(16, 1), jnp.float32)}

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, batch["x"])["params"],
                             tx=optax.adam(1e-2))

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))

  def loss_fn(params, b, rng):
    pred = model.apply({"params": params}, b["x"])
    return jnp.mean((pred - b["y"]) ** 2), {}

  step = parallelize(make_train_step(loss_fn), mesh, shardings)
  fit(step, state, [batch], num_steps=6, checkpoint_dir=ckpt,
      checkpoint_every=3, log_every=2, shardings=shardings)
  fit_step_cache = step.jitted._cache_size()

  exported = tracer.export(trace_path)

  return {
      "trace_path": exported,
      "fit_trace_path": str(work / "ck" / "trace.json"),
      "metrics_path": str(work / "ck" / "metrics.jsonl"),
      "uids": [f"req{i}" for i in range(4)],
      "outputs": outputs,
      "fit_step_cache": fit_step_cache,
      "engine_step_cache": engine_step_cache,
      "engine_step_cache_after_timing": engine_step_cache_after,
      "times_on": times[True],
      "times_off": times[False],
  }


# ------------------------------------------------------------ acceptance


@pytest.mark.quick
def test_trace_schema_valid(traced_run):
  """Acceptance + `make trace-demo` CI check: the emitted Chrome-trace
  JSON is schema-valid — traceEvents list, required keys per event,
  monotonic ts, strictly paired B/E — and Perfetto-loadable in shape."""
  events = validate_trace(traced_run["trace_path"])
  assert events, "empty trace"
  with open(traced_run["trace_path"]) as f:
    doc = json.load(f)
  assert isinstance(doc["traceEvents"], list)
  # Re-assert the schema independently of the validator's internals.
  last = None
  for ev in doc["traceEvents"]:
    assert {"ph", "name", "pid", "tid"} <= set(ev), ev
    if ev["ph"] == "M":
      continue
    assert "ts" in ev, ev
    if last is not None:
      assert ev["ts"] >= last, "non-monotonic ts"
    last = ev["ts"]
  # fit() auto-exported its own trace under the checkpoint dir too.
  validate_trace(traced_run["fit_trace_path"])


@pytest.mark.quick
def test_request_lifecycle_tracks_complete(traced_run):
  """Acceptance: every request has one complete lifecycle — submit
  instant, an admit->retire span carrying the finish reason, at least
  one prefill chunk and one decode/speculate span nested in it on the
  same slot track, a first-token instant, and (since the same-params
  drafter always drafts) speculate spans with accepted counts."""
  events = validate_trace(traced_run["trace_path"])
  spans, unmatched = report.pair_spans(events)
  assert unmatched == 0
  by_uid = {s["args"]["uid"]: s for s in spans
            if s["cat"] == "serving.request"}
  submits = {e["args"]["uid"] for e in events
             if e.get("ph") == "i" and e["name"] == "serving/submit"}
  firsts = {e["args"]["uid"] for e in events
            if e.get("ph") == "i" and e["name"] == "serving/first_token"}
  assert set(traced_run["uids"]) <= set(by_uid)
  assert set(traced_run["uids"]) <= submits
  assert set(traced_run["uids"]) <= firsts
  speculated = 0
  for uid in traced_run["uids"]:
    req = by_uid[uid]
    t0, t1 = req["ts"], req["ts"] + req["dur"]
    inner = [s for s in spans if s["tid"] == req["tid"]
             and s["name"] in ("prefill", "decode", "speculate")
             and t0 <= s["ts"] and s["ts"] + s["dur"] <= t1 + 1e-9]
    assert any(s["name"] == "prefill" for s in inner), uid
    decodes = [s for s in inner if s["name"] in ("decode", "speculate")]
    assert decodes, uid
    assert req["args"]["finish_reason"] == "length"
    assert req["args"]["new_tokens"] >= 1
    for s in inner:
      if s["name"] == "speculate":
        assert s["args"]["drafted"] >= 1
        assert 0 <= s["args"]["accepted"] <= s["args"]["drafted"]
        speculated += 1
  assert speculated > 0, "no speculate spans despite a drafting engine"
  # The per-request report rolls the same events up without error.
  timelines = {t["uid"]: t for t in report.request_timelines(events)}
  assert set(traced_run["uids"]) <= set(timelines)
  assert all(t["ttft_us"] is not None and t["prefill_chunks"] >= 1
             for t in timelines.values())


@pytest.mark.quick
def test_tracing_overhead_and_zero_recompile(traced_run):
  """Acceptance: tracing changes nothing the runtime can feel — the
  fused serving step and the fit train step each stay at ONE compiled
  program with tracing on, and traced step time is within 5% of
  untraced on the CPU mesh, judged over ~70 identical interleaved
  per-step samples per side.  Real tracing overhead taxes EVERY traced
  step, so it must show up in both the median and the floor; a shared
  2-core box instead perturbs one estimator at a time (a load phase
  shifts the median, one lucky scheduler slot shifts the min), so the
  guard passes when EITHER estimator is within budget."""
  assert traced_run["fit_step_cache"] == 1
  assert traced_run["engine_step_cache"] == 1
  assert traced_run["engine_step_cache_after_timing"] == 1
  assert len(traced_run["times_on"]) >= 50
  assert len(traced_run["times_off"]) >= 50
  on_med = statistics.median(traced_run["times_on"])
  off_med = statistics.median(traced_run["times_off"])
  on_min = min(traced_run["times_on"])
  off_min = min(traced_run["times_off"])
  within = lambda a, b: a <= b * 1.05 + 1e-4  # noqa: E731
  assert within(on_med, off_med) or within(on_min, off_min), (
      f"traced step med/min {on_med * 1e6:.0f}/{on_min * 1e6:.0f}us vs "
      f"untraced {off_med * 1e6:.0f}/{off_min * 1e6:.0f}us")


@pytest.mark.quick
def test_fit_phase_spans_and_namespaced_auto_metrics(traced_run):
  """The train loop's phases and the checkpoint stage/commit appear as
  spans, and fit() auto-built the namespaced JSONL sink (satellite:
  runs are never silently unlogged)."""
  events = validate_trace(traced_run["fit_trace_path"])
  names = {e["name"] for e in events}
  for expected in ("train/data_next", "train/step_dispatch",
                   "train/metrics_flush", "train/host_sync",
                   "checkpoint/stage", "checkpoint/commit"):
    assert expected in names, expected
  lines = [json.loads(l) for l in open(traced_run["metrics_path"])]
  assert lines, "auto metrics sink wrote nothing"
  assert all("train/loss" in l for l in lines)
  assert all(k in ("step", "time") or k.split("/")[0] in
             ("train", "serving", "comm", "resilience")
             for l in lines for k in l)


def test_tracer_is_sync_free_under_transfer_guard():
  """No added per-step host syncs: every tracer primitive runs inside a
  device->host transfer-guard disallow region around jitted steps."""
  tracer = trace_lib.Tracer(enabled=True, ring_capacity=4096)
  f = jax.jit(lambda x: x * 2 + 1)
  y = f(jnp.ones((8, 8)))  # compile + one result outside the guard
  with jax.transfer_guard_device_to_host("disallow"):
    for i in range(20):
      with tracer.span("step", cat="train", track="train"):
        y = f(y)
      tracer.instant("tick", args={"i": i})
      tracer.counter("depth", i)
  assert f._cache_size() == 1
  assert float(y[0, 0]) != 0.0  # sync deferred past the guard


# ------------------------------------------------------------- tracer unit


def test_tracer_ring_capacity_and_dropped_count():
  tracer = trace_lib.Tracer(enabled=True, ring_capacity=4)
  for i in range(10):
    tracer.instant(f"e{i}")
  events = [e for e in tracer.events() if e["ph"] == "i"]
  assert [e["name"] for e in events] == ["e6", "e7", "e8", "e9"]
  assert tracer.dropped == 6


def test_tracer_concurrent_recording_is_consistent():
  # The watchdog monitor thread records instants while the main thread
  # records spans: track registration must never hand out a duplicate
  # tid, and the eviction accounting must not lose increments (`+=` is
  # not GIL-atomic).
  import threading
  tracer = trace_lib.Tracer(enabled=True, ring_capacity=64)
  n = 2000

  def monitor():
    for i in range(n):
      tracer.instant("timeout", track=f"watchdog {i % 7}")

  t = threading.Thread(target=monitor)
  t.start()
  for i in range(n):
    with tracer.span("step", track=f"slot {i % 7}"):
      pass
  t.join()
  total = n + 2 * n  # instants + B/E pairs
  assert tracer._n_appended == total
  assert tracer.dropped == total - len(tracer._events)
  tids = list(tracer._tracks.values())
  assert len(tids) == len(set(tids))  # no duplicate tid handed out


def test_tracer_sampling_is_deterministic():
  tracer = trace_lib.Tracer(enabled=True, sample_rate=0.5)
  kept = 0
  for _ in range(10):
    with tracer.span("s", sample=True):
      kept = sum(1 for e in tracer.events() if e["ph"] == "B")
  assert kept == 5  # exactly every other sampled span
  # Unsampled spans and a rate of 1.0 record everything.
  with tracer.span("always"):
    pass
  assert sum(1 for e in tracer.events()
             if e["ph"] == "B" and e["name"] == "always") == 1


def test_tracer_sampling_keeps_whole_steps_together():
  # fit() makes ONE sampling decision per step (sample_tick) and gates
  # every train/* phase span on it (record=) — so a sampled step keeps
  # its FULL phase set, including phases only some steps reach (host
  # sync runs on log boundaries only), instead of each span's sampling
  # aliasing against fit's fixed phase sequence.
  tracer = trace_lib.Tracer(enabled=True, sample_rate=0.25)
  all_phases = {"data_next", "step_dispatch", "host_sync"}
  recorded = []  # (step, phase) pairs that made it into the ring
  for step in range(8):
    rec = tracer.sample_tick("train")
    phases = ["data_next", "step_dispatch"]
    if step % 2 == 1:  # log-boundary-only phase
      phases.append("host_sync")
    for phase in phases:
      before = len(tracer._events)
      with tracer.span(phase, record=rec):
        pass
      if len(tracer._events) > before:
        recorded.append((step, phase))
  steps = {s for s, _ in recorded}
  assert steps == {3, 7}  # every 4th step, deterministically
  for s in steps:  # and each sampled step kept all of its phases
    assert {p for st, p in recorded if st == s} == all_phases


def test_tracer_disabled_is_noop_and_null_span_shared():
  tracer = trace_lib.Tracer(enabled=False, ring_capacity=8)
  s1 = tracer.span("a")
  s2 = tracer.span("b", sample=True)
  assert s1 is s2  # the shared null context manager: no allocation
  with s1:
    tracer.instant("x")
    tracer.counter("c", 1)
  assert not list(tracer._events)


def test_validate_trace_catches_malformed():
  with pytest.raises(ValueError, match="monotonic"):
    validate_trace([
        {"ph": "B", "name": "a", "pid": 0, "tid": 0, "ts": 2.0},
        {"ph": "E", "name": "a", "pid": 0, "tid": 0, "ts": 1.0}])
  with pytest.raises(ValueError, match="unclosed"):
    validate_trace([{"ph": "B", "name": "a", "pid": 0, "tid": 0,
                     "ts": 1.0}])
  with pytest.raises(ValueError, match="no open B"):
    validate_trace([{"ph": "E", "name": "a", "pid": 0, "tid": 0,
                     "ts": 1.0}])
  with pytest.raises(ValueError, match="missing"):
    validate_trace([{"ph": "B", "name": "a", "ts": 1.0}])
  with pytest.raises(ValueError, match="traceEvents"):
    validate_trace({"foo": []})


def test_ensure_configured_follows_config_and_explicit_install_wins():
  trace_lib.reset()
  epl.init(epl.Config({"observability.enabled": True,
                       "observability.ring_capacity": 128}))
  t1 = trace_lib.ensure_configured()
  assert t1.enabled and t1.ring_capacity == 128
  assert trace_lib.ensure_configured() is t1  # same config -> same tracer
  epl.init()  # observability off again
  assert not trace_lib.ensure_configured().enabled
  mine = trace_lib.Tracer(enabled=True, ring_capacity=16)
  trace_lib.install(mine)
  epl.init()
  assert trace_lib.ensure_configured() is mine  # explicit install wins
  trace_lib.reset()


def test_ensure_configured_foreign_config_cannot_drop_tracer():
  # A component constructed mid-run with its own explicit config (an
  # engine built with serving knobs, observability default-off there)
  # must not tear down or rebuild the run's tracer — either would
  # silently discard the recorded ring and stop every other site's
  # instrumentation.
  trace_lib.reset()
  epl.init(epl.Config({"observability.enabled": True}))
  t1 = trace_lib.ensure_configured()
  with t1.span("train/step"):
    pass
  foreign_off = epl.Config({"serving.num_slots": 2})
  assert trace_lib.ensure_configured(foreign_off) is t1
  foreign_differs = epl.Config({"observability.enabled": True,
                                "observability.ring_capacity": 32})
  assert trace_lib.ensure_configured(foreign_differs) is t1  # no rebuild
  assert len(t1._events) == 2  # the ring survived both
  # The ambient Env config still reconciles destructively as documented.
  epl.init()
  assert not trace_lib.ensure_configured().enabled
  trace_lib.reset()


# ----------------------------------------------------------- registry unit


class _ListSink:
  def __init__(self):
    self.records = []
    self.closed = False

  def write(self, step, metrics):
    self.records.append((step, dict(metrics)))

  def flush(self):
    pass

  def close(self):
    self.closed = True


def test_metric_registry_namespaces_and_schema():
  sink = _ListSink()
  reg = MetricRegistry(sink)
  reg.publish(1, {"loss": 0.5}, "train")
  reg.publish(1, {"tokens_per_s": 10.0}, "serving")
  reg.publish_many(2, {"train": {"loss": 0.4},
                       "resilience": {"bad_steps": 1},
                       "comm": {}})
  assert sink.records[0] == (1, {"train/loss": 0.5})
  assert sink.records[1] == (1, {"serving/tokens_per_s": 10.0})
  # publish_many merges namespaces into ONE record; empty ones vanish.
  assert sink.records[2] == (2, {"train/loss": 0.4,
                                 "resilience/bad_steps": 1})
  assert reg.latest()["train/loss"] == 0.4
  with pytest.raises(ValueError, match="namespace"):
    reg.publish(3, {"x": 1}, "bogus")
  # Sub-namespaces validate by their root.
  reg.publish(3, {"x": 1}, "serving/slot0")
  assert sink.records[-1] == (3, {"serving/slot0/x": 1})
  reg.close()
  assert sink.closed


def test_registry_feeds_metrics_writer_and_serving_stats(tmp_path):
  path = str(tmp_path / "m.jsonl")
  stats = ServingStats(clock=iter(range(100)).__next__)
  stats.note_submitted("a")
  stats.note_admitted("a")
  stats.note_first_token("a")
  stats.note_finished("a", 3)
  stats.note_step(1, 2, 4, 1, 0.5)
  with MetricsWriter(path) as w:
    reg = MetricRegistry(w)
    stats.publish(reg, step=7)
  (line,) = [json.loads(l) for l in open(path)]
  assert line["step"] == 7
  assert line["serving/finished_requests"] == 1.0
  assert line["serving/tokens_per_s"] > 0


def test_flops_profiler_publishes_split_namespaces():
  sink = _ListSink()
  prof = FlopsProfiler(flops_per_step=1e9, every_n_steps=1,
                       comm_bytes_per_step=1e6,
                       registry=MetricRegistry(sink))
  prof.note_bad_step(2)
  prof.step()          # first call only arms the timer
  stats = prof.step()
  assert stats is not None
  (_, record), = sink.records[-1:]
  assert "train/step_time_s" in record
  assert "comm/comm_share" in record
  assert record["resilience/bad_steps"] == 2.0


# ----------------------------------------------------- satellite coverage


def test_metrics_writer_array_summary_not_repr(tmp_path):
  """Satellite: multi-element device/np arrays flush as a compact
  {shape, dtype, mean} summary, not a multi-kilobyte str() dump."""
  path = str(tmp_path / "m.jsonl")
  big = np.arange(2048, dtype=np.float32).reshape(32, 64)
  with MetricsWriter(path) as w:
    w.write(1, {"loss": jnp.float32(0.5), "grads_debug": big,
                "device_vec": jnp.arange(3.0), "note": "hello"})
  (line,) = [json.loads(l) for l in open(path)]
  assert line["loss"] == 0.5
  assert line["grads_debug"] == {"shape": [32, 64], "dtype": "float32",
                                 "mean": pytest.approx(1023.5)}
  assert line["device_vec"]["shape"] == [3]
  assert line["note"] == "hello"
  # The compact record is ~60 bytes; the old repr was thousands.
  assert len(json.dumps(line["grads_debug"])) < 200


def test_tensorboard_writer_missing_dep_actionable(monkeypatch):
  """Satellite: absent tensorboardX raises at CONSTRUCTION with
  install guidance, instead of silently dropping metrics later."""
  monkeypatch.setitem(sys.modules, "tensorboardX", None)
  from easyparallellibrary_tpu.utils.metrics_writer import (
      TensorBoardWriter)
  with pytest.raises(ImportError, match="tensorboardX"):
    TensorBoardWriter(logdir="/tmp/unused_tb")


def test_serving_stats_empty_and_reset_windows():
  """Satellite: summary() on a fresh or reset window never raises and
  degrades every rollup to 0.0."""
  stats = ServingStats()
  empty = stats.summary()
  assert empty["steps"] == 0.0
  assert empty["tokens_per_s"] == 0.0
  assert empty["ttft_p99_s"] == 0.0
  assert empty["acceptance_rate"] == 0.0
  assert all(isinstance(v, float) for v in empty.values())
  stats.note_submitted("a")
  stats.note_finished("a", 2)
  stats.note_step(1, 2, 0, 1, 0.1, drafted_tokens=2, accepted_tokens=1)
  assert stats.summary()["generated_tokens"] == 2.0
  stats.reset()
  assert stats.summary() == empty


def test_report_cli_prints_breakdown(traced_run, capsys):
  """`python -m easyparallellibrary_tpu.observability.report <trace>`
  prints the span table and per-request timelines."""
  assert report.main([traced_run["trace_path"]]) == 0
  out = capsys.readouterr().out
  assert "prefill" in out
  assert "req0" in out
  assert "finish" in out
  assert "serving/device_step" in out
