"""ZeRO opt-state sharding tests (reference analog: tests/zero_test.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn
from jax.sharding import PartitionSpec as P

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.parallel import (
    TrainState, create_sharded_train_state, make_train_step, parallelize)


class Net(nn.Module):
  @nn.compact
  def __call__(self, x):
    x = nn.Dense(64)(x)
    return nn.Dense(8)(x)


def _build(zero_level):
  env = epl.init(epl.Config({"zero.level": zero_level} if zero_level else {}))
  with epl.replicate(1):
    model = Net()
  mesh = epl.current_plan().build_mesh()
  x = jnp.ones((16, 32))
  tx = optax.adam(1e-2)

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, x)["params"], tx=tx)

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0), zero_level=zero_level)
  return model, mesh, state, shardings, x


def test_zero_v0_shards_opt_state_on_data_axis():
  _, mesh, state, shardings, _ = _build("v0")
  # Adam mu/nu for the Dense kernels must be sharded over data.
  specs = jax.tree_util.tree_leaves(
      jax.tree_util.tree_map(lambda s: s.spec, shardings.opt_state,
                             is_leaf=lambda x: hasattr(x, "spec")))
  assert any("data" in str(s) for s in specs)
  # Params remain replicated (ZeRO-1 semantics).
  pspecs = jax.tree_util.tree_leaves(
      jax.tree_util.tree_map(lambda s: s.spec, shardings.params,
                             is_leaf=lambda x: hasattr(x, "spec")))
  assert all(s == P() for s in pspecs)


@pytest.mark.quick
def test_zero_training_matches_baseline():
  def run(zero_level):
    model, mesh, state, shardings, x = _build(zero_level)
    y = jnp.ones((16, 8))

    def loss_fn(params, batch, rng):
      pred = model.apply({"params": params}, batch["x"])
      return jnp.mean((pred - batch["y"]) ** 2), {}

    step = parallelize(make_train_step(loss_fn), mesh, shardings)
    rng = jax.random.PRNGKey(1)
    losses = []
    for _ in range(5):
      state, m = step(state, {"x": x, "y": y}, rng)
      losses.append(float(m["loss"]))
    return losses

  np.testing.assert_allclose(run("v0"), run(""), rtol=1e-5)
  np.testing.assert_allclose(run("v1"), run(""), rtol=1e-5)


def _loss_fn(model):
  def loss_fn(params, batch, rng):
    pred = model.apply({"params": params}, batch["x"])
    return jnp.mean((pred - batch["y"]) ** 2), {}
  return loss_fn


def test_explicit_zero1_matches_gspmd_baseline():
  """The explicit reduce-scatter -> owner-apply -> all-gather step trains
  identically to the implicit GSPMD path (reference: reduce-to-owner +
  broadcast choreography, epl/runtime/zero.py:129-190)."""
  from easyparallellibrary_tpu.runtime.zero import make_zero1_train_step

  model, mesh, state, shardings, x = _build("v1")
  y = jnp.ones((16, 8))
  loss_fn = _loss_fn(model)
  zstep = make_zero1_train_step(loss_fn, mesh)

  base_model, base_mesh, base_state, base_shardings, _ = _build("")
  bstep = parallelize(make_train_step(loss_fn), base_mesh, base_shardings)

  rng = jax.random.PRNGKey(1)
  for _ in range(5):
    state, zm = zstep(state, {"x": x, "y": y}, rng)
    base_state, bm = bstep(base_state, {"x": x, "y": y}, rng)
    np.testing.assert_allclose(float(zm["loss"]), float(bm["loss"]),
                               rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                              rtol=1e-4, atol=1e-6),
      state.params, base_state.params)
  # Optimizer state is genuinely sharded: adam mu for the 32x64 kernel
  # holds a 1/8 slice per device.
  mu = state.opt_state[0].mu["Dense_0"]["kernel"]
  mu = mu.value if hasattr(mu, "value") else mu
  assert mu.sharding.shard_shape(mu.shape) != mu.shape


def test_explicit_zero1_reduces_per_device_state_bytes():
  """Measured HBM claim (VERDICT item 6): compiled per-device argument
  bytes of the v1 step are smaller than the unsharded-opt DP step."""
  from easyparallellibrary_tpu.runtime.zero import make_zero1_train_step

  model, mesh, state, shardings, x = _build("v1")
  y = jnp.ones((16, 8))
  loss_fn = _loss_fn(model)

  zstep = make_zero1_train_step(loss_fn, mesh)
  zstep(state, {"x": x, "y": y}, jax.random.PRNGKey(1))  # build + donate

  base_model, base_mesh, base_state, base_shardings, _ = _build("")
  bstep = parallelize(make_train_step(loss_fn), base_mesh, base_shardings)

  # Fresh (undonated) state with the SAME pytree metadata for lowering;
  # compare per-device argument (resident state) sizes.
  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, x)["params"],
                             tx=state.tx)

  state2, _ = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0), zero_level="v1")
  zmem = zstep.jitted.lower(
      state2, {"x": x, "y": y}, jax.random.PRNGKey(1)
  ).compile().memory_analysis()
  bmem = bstep.jitted.lower(
      base_state, {"x": x, "y": y}, jax.random.PRNGKey(1)
  ).compile().memory_analysis()
  assert zmem.argument_size_in_bytes < bmem.argument_size_in_bytes, (
      zmem.argument_size_in_bytes, bmem.argument_size_in_bytes)


def test_explicit_zero1_rejects_coupled_optimizer():
  """Leaf-coupling transforms (global-norm clip) would be computed over
  1/dp shards; the step must refuse them with guidance instead of
  silently mis-clipping (reference constraint checks:
  epl/runtime/zero.py:60-75)."""
  import optax
  import pytest
  from easyparallellibrary_tpu.runtime.zero import make_zero1_train_step

  model, mesh, state, shardings, x = _build("v1")
  state = state.replace(
      tx=optax.chain(optax.clip_by_global_norm(1.0), optax.adam(1e-2)))
  state = state.replace(opt_state=state.tx.init(
      jax.tree_util.tree_map(lambda l: l, state.params)))
  zstep = make_zero1_train_step(_loss_fn(model), mesh)
  with pytest.raises(ValueError, match="elementwise"):
    zstep(state, {"x": x, "y": jnp.ones((16, 8))}, jax.random.PRNGKey(0))


def test_explicit_zero1_probe_handles_structure_and_slices():
  """The guard probes with the REAL param structure (so optax.masked
  passes) and detects within-leaf coupling (clip_by_block_rms raises)."""
  import optax
  import pytest
  from easyparallellibrary_tpu.runtime.zero import _assert_elementwise_tx

  params = {"dense": {"kernel": jnp.ones((4, 4)), "bias": jnp.ones((4,))}}
  masked = optax.masked(optax.adam(1e-2),
                        {"dense": {"kernel": True, "bias": False}})
  _assert_elementwise_tx(masked, params)  # must not raise

  rms = optax.chain(optax.clip_by_block_rms(1.0), optax.adam(1e-2))
  with pytest.raises(ValueError, match="elementwise"):
    _assert_elementwise_tx(rms, params)

  _assert_elementwise_tx(optax.adamw(1e-3), params)  # plain case still ok


def test_explicit_zero1_probe_catches_factored_adafactor():
  """ADVICE r3: optax's factored RMS statistics only factor leaves whose
  dims reach min_dim_size_to_factor (128), so a tiny probe would pass
  adafactor as elementwise while real-size leaves couple positions.  The
  128x128 probe must reject it."""
  import optax
  import pytest
  from easyparallellibrary_tpu.runtime.zero import _assert_elementwise_tx

  params = {"dense": {"kernel": jnp.ones((4, 4)), "bias": jnp.ones((4,))}}
  ada = optax.adafactor(learning_rate=1e-3, clipping_threshold=None)
  with pytest.raises(ValueError, match="elementwise"):
    _assert_elementwise_tx(ada, params)
  # Default adafactor (with update clipping, also coupled) too.
  with pytest.raises(ValueError, match="elementwise"):
    _assert_elementwise_tx(optax.adafactor(learning_rate=1e-3), params)


@pytest.mark.slow
def test_zero_v1_smap_engine_matches_baseline():
  """ZeRO-1 x smap engine (VERDICT r4 item 5): with zero.level="v1" the
  engine's grad reduction becomes a reduce-scatter to the data-axis
  owner (grads leave the engine data-sharded, pre-aligned with the v1
  optimizer-state shards).  The training trajectory must match the
  plain smap engine exactly, and the lowered program must carry a
  reduce-scatter."""
  from easyparallellibrary_tpu.models import GPT, GPTConfig
  from easyparallellibrary_tpu.models.gpt import make_gpt_train_step

  def run(zero_level):
    conf = {"pipeline.engine": "smap"}
    if zero_level:
      conf["zero.level"] = zero_level
    env = epl.init(epl.Config(conf))
    cfg = GPTConfig(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
                    d_ff=64, max_seq_len=16, dtype=jnp.float32,
                    pipeline_stages=2, num_micro_batch=2)
    with epl.replicate(1):
      model = GPT(cfg)
    mesh = env.cluster.build_mesh(stage=2)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 17)),
                      jnp.int32)

    def init_fn(rng):
      return TrainState.create(
          apply_fn=model.apply,
          params=model.init(rng, ids[:, :-1])["params"],
          tx=optax.adam(1e-2))

    state, shardings = create_sharded_train_state(
        init_fn, mesh, jax.random.PRNGKey(0), zero_level=zero_level)
    if zero_level:
      # v1 opt-state leaves really are data-sharded.
      specs = jax.tree_util.tree_leaves(
          jax.tree_util.tree_map(lambda s: s.spec, shardings.opt_state,
                                 is_leaf=lambda x: hasattr(x, "spec")))
      assert any("data" in str(s) for s in specs)
    step = parallelize(make_gpt_train_step(model), mesh, shardings)
    losses = []
    for i in range(4):
      state, m = step(state, {"ids": ids}, jax.random.PRNGKey(i))
      losses.append(float(m["loss"]))
    if zero_level:
      txt = step.jitted.lower(
          state, {"ids": ids}, jax.random.PRNGKey(9)).as_text()
      assert "reduce-scatter" in txt or "reduce_scatter" in txt
    return losses

  np.testing.assert_allclose(run("v1"), run(""), rtol=2e-5)


def test_explicit_zero1_probe_catches_adafactor_at_current_default():
  """Version-pin for the probe threshold (VERDICT r4 weak #6): the
  128x128 probe is sized to trip optax's factored-RMS statistics at
  their min_dim_size_to_factor default.  If a future optax raises that
  default above 128, adafactor would silently pass the probe as
  elementwise — this test fails first, telling us to resize the probe."""
  import inspect
  import optax
  import pytest
  from easyparallellibrary_tpu.runtime.zero import _assert_elementwise_tx

  sig = inspect.signature(optax.scale_by_factored_rms)
  default = sig.parameters["min_dim_size_to_factor"].default
  assert default <= 128, (
      f"optax min_dim_size_to_factor default changed to {default}: "
      "resize the probe in runtime.zero._assert_elementwise_tx to at "
      "least that size")
  params = {"w": jnp.ones((4, 4))}
  with pytest.raises(ValueError, match="elementwise"):
    _assert_elementwise_tx(optax.adafactor(1e-3), params)


def test_zero_v1_smap_interleaved_and_tp_match_baseline():
  """ZeRO-1 composes with the interleaved schedule (K-stacked leaves:
  the owner dim maps +1 past the chunk axis) and with TP (meta-sharded
  model dims are skipped by the owner-dim choice)."""
  from easyparallellibrary_tpu.models import GPT, GPTConfig
  from easyparallellibrary_tpu.models.gpt import make_gpt_train_step

  def run(zero_level):
    conf = {"pipeline.engine": "smap"}
    if zero_level:
      conf["zero.level"] = zero_level
    env = epl.init(epl.Config(conf))
    cfg = GPTConfig(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
                    d_ff=64, max_seq_len=16, dtype=jnp.float32,
                    pipeline_stages=2, num_micro_batch=2,
                    pipeline_interleave=2, tensor_parallel=True)
    with epl.replicate(1):
      model = GPT(cfg)
    mesh = env.cluster.build_mesh(stage=2, model=2)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 17)),
                      jnp.int32)

    def init_fn(rng):
      return TrainState.create(
          apply_fn=model.apply,
          params=model.init(rng, ids[:, :-1])["params"],
          tx=optax.adam(1e-2))

    state, sh = create_sharded_train_state(
        init_fn, mesh, jax.random.PRNGKey(0), zero_level=zero_level)
    step = parallelize(make_gpt_train_step(model), mesh, sh)
    losses = []
    for i in range(3):
      state, m = step(state, {"ids": ids}, jax.random.PRNGKey(i))
      losses.append(float(m["loss"]))
    if zero_level:
      txt = step.jitted.lower(state, {"ids": ids},
                              jax.random.PRNGKey(9)).as_text()
      assert "reduce-scatter" in txt or "reduce_scatter" in txt
    return losses

  np.testing.assert_allclose(run("v1"), run(""), rtol=2e-5)


def test_zero1_owner_dim_rule_shared_across_layouts():
  """The engines' grad owner dims (pipeline_smap.zero1_grad_layout) and
  the optimizer-state layout (runtime.zero._shard_leaf_spec) both
  delegate to runtime.zero.zero_owner_dim — assert the chosen dims agree
  on K=1 (stage-stacked), K>1 (stacked with the inserted '_chunk' axis)
  and TP (model-sharded) trees, so scattered grads always land on the
  owner's optimizer shard without a GSPMD reshard."""
  import types
  from easyparallellibrary_tpu.parallel.pipeline_smap import (
      zero1_grad_layout)
  from easyparallellibrary_tpu.runtime.zero import _shard_leaf_spec

  dp = 4
  leaf = lambda *s: types.SimpleNamespace(shape=s)  # noqa: E731
  un = {
      "k1": leaf(16, 8),          # K=1 stage-stacked trunk leaf
      "k2": leaf(4, 2, 16, 8),    # K>1: chunk axis stacked at dim 1
      "tp": leaf(16, 8),          # TP leaf: model axis on dim 1
      "small": leaf(3, 2),        # nothing divisible -> replicated
  }
  full = {"k1": P("stage"), "k2": P("stage", "_chunk"),
          "tp": P(None, "model"), "small": P()}
  man = {"k1": P("stage"), "k2": P("stage"), "tp": P(), "small": P()}
  dims, out_specs = zero1_grad_layout(un, full, man, dp)
  assert dims == {"k1": 1, "k2": 2, "tp": 0, "small": -1}

  # Agreement with the optimizer-state rule on the same leaves:
  assert _shard_leaf_spec(leaf(16, 8), P("stage"), dp) == \
      P("stage", "data")                          # dim 1 == dims["k1"]
  assert _shard_leaf_spec(leaf(16, 8), P(None, "model"), dp) == \
      P("data", "model")                          # dim 0 == dims["tp"]
  # K>1: shard_opt_state sees the PER-PASS leaf [S, 16, 8]; the engine
  # sees it stacked with a chunk axis inserted at dim 1, so the engine's
  # dim must be the per-pass dim + 1.
  per_pass = _shard_leaf_spec(leaf(4, 16, 8), P("stage"), dp)
  per_pass_dim = list(per_pass).index("data")
  assert dims["k2"] == per_pass_dim + 1
  # Replicated leaves stay replicated under both rules.
  assert _shard_leaf_spec(leaf(3, 2), P(), dp) == P()
  # The owner spec adds `data` exactly at the chosen dim.
  assert out_specs["k1"] == P("stage", "data")
  assert out_specs["tp"] == P("data", None)
