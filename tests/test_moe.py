"""MoE expert-parallel tests (reference analog: tests/split_test.py's
einsum-MoE FFN coverage)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.models.gpt import gpt_loss
from easyparallellibrary_tpu.models.moe import MoEMLP
from easyparallellibrary_tpu.parallel import (
    TrainState, create_sharded_train_state, make_train_step, parallelize)

CFG = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=16,
                d_ff=32, max_seq_len=8, dtype=jnp.float32,
                num_experts=4, capacity_factor=2.0)


@pytest.mark.quick
def test_moe_forward_matches_naive_routing():
  """With ample capacity, output == per-token expert(token) * gate."""
  moe = MoEMLP(dataclasses.replace(CFG, capacity_factor=8.0))
  x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16), jnp.float32)
  variables = moe.init(jax.random.PRNGKey(0), x)
  params = variables["params"]
  out = moe.apply({"params": params}, x, mutable=["losses"])[0]

  # Naive reference: route each token independently.
  rk = params["router_kernel"].value
  wi, wo = params["wi"].value, params["wo"].value
  tokens = x.reshape(-1, 16)
  probs = jax.nn.softmax(tokens @ rk, axis=-1)
  idx = jnp.argmax(probs, axis=-1)
  gate = jnp.max(probs, axis=-1)
  ref = []
  for t in range(tokens.shape[0]):
    e = int(idx[t])
    h = jax.nn.gelu(tokens[t] @ wi[e])
    ref.append((h @ wo[e]) * gate[t])
  ref = jnp.stack(ref).reshape(2, 8, 16)
  np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_moe_capacity_drops_tokens():
  """With capacity 1 token/expert, most tokens are dropped (output 0)."""
  cfg = dataclasses.replace(CFG, capacity_factor=4 / 16)  # C = 1
  moe = MoEMLP(cfg)
  x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16), jnp.float32)
  variables = moe.init(jax.random.PRNGKey(0), x)
  out = moe.apply(variables, x, mutable=["losses"])[0]
  zero_rows = np.sum(np.all(np.abs(np.asarray(out).reshape(-1, 16)) < 1e-12,
                            axis=-1))
  assert zero_rows >= 16 - 4  # at most E=4 tokens survive with C=1


def test_moe_top2_routes_more_mass():
  moe1 = MoEMLP(dataclasses.replace(CFG, capacity_factor=8.0), top_k=1)
  moe2 = MoEMLP(dataclasses.replace(CFG, capacity_factor=8.0), top_k=2)
  x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16), jnp.float32)
  v = moe1.init(jax.random.PRNGKey(0), x)
  out1 = moe1.apply(v, x, mutable=["losses"])[0]
  out2 = moe2.apply(v, x, mutable=["losses"])[0]
  # top-2 adds the second expert's contribution; outputs must differ.
  assert float(jnp.mean(jnp.abs(out1 - out2))) > 1e-6


def test_moe_gpt_trains_on_expert_mesh():
  env = epl.init()
  with epl.replicate(1):
    model = GPT(CFG)
  plan = epl.current_plan(expert_parallel=4)
  mesh = plan.build_mesh()
  assert dict(zip(mesh.axis_names, mesh.devices.shape))["expert"] == 4

  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 9)),
                    jnp.int32)
  batch = {"ids": ids}
  tx = optax.adam(1e-2)

  def init_fn(rng):
    return TrainState.create(
        apply_fn=model.apply,
        params=model.init(rng, ids[:, :-1])["params"], tx=tx)

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  # Expert weights sharded over the expert axis.
  wi = state.params["block_1"]["moe"]["wi"].value
  assert wi.sharding.shard_shape(wi.shape)[0] == 1

  step = parallelize(
      make_train_step(lambda p, b, r: gpt_loss(model, p, b, r)),
      mesh, shardings)
  losses = []
  for _ in range(8):
    state, m = step(state, batch, jax.random.PRNGKey(1))
    losses.append(float(m["loss"]))
  assert losses[-1] < losses[0]
  assert "moe_aux_loss" in m
  assert float(m["moe_aux_loss"]) > 0.0


def test_moe_aux_loss_near_one_for_balanced():
  """Perfectly balanced routing gives aux ~= 1.0 (E * (1/E) * (1/E) * E)."""
  moe = MoEMLP(dataclasses.replace(CFG, capacity_factor=8.0))
  x = jnp.asarray(np.random.RandomState(3).randn(4, 8, 16), jnp.float32)
  v = moe.init(jax.random.PRNGKey(1), x)
  _, state = moe.apply(v, x, mutable=["losses"])
  aux = float(jax.tree_util.tree_leaves(state["losses"])[0])
  assert 0.5 < aux < 4.0  # near-uniform at random init


def test_moe_every_one_uses_experts_in_all_blocks():
  cfg = dataclasses.replace(CFG, moe_every=1)
  model = GPT(cfg)
  ids = jnp.zeros((2, 5), jnp.int32)
  params = model.init(jax.random.PRNGKey(0), ids)["params"]
  assert "moe" in params["block_0"] and "moe" in params["block_1"]


def test_moe_aux_loss_sees_pre_drop_imbalance():
  """With capacity 1, a collapsed router must still show high aux loss."""
  moe = MoEMLP(dataclasses.replace(CFG, capacity_factor=4 / 16))
  x = jnp.ones((2, 8, 16), jnp.float32)  # identical tokens -> one expert
  v = moe.init(jax.random.PRNGKey(0), x)
  _, state = moe.apply(v, x, mutable=["losses"])
  aux = float(jax.tree_util.tree_leaves(state["losses"])[0])
  # All 16 tokens routed to 1 of 4 experts: aux ~= E * 1 * p_max >= 1.
  assert aux > 1.0


def test_moe_a2a_impl_matches_einsum():
  """The explicit all_to_all expert-parallel path (reference M6-style EP:
  NCCL AllToAll around the expert einsums, epl/parallel/hooks.py:758-794)
  computes the same outputs and gradients as the einsum path under ample
  capacity, on a real expert=4 mesh."""
  env = epl.init()
  env.cluster.build_mesh(expert=4)
  cfg = dataclasses.replace(CFG, capacity_factor=8.0)
  x = jnp.asarray(np.random.RandomState(0).randn(4, 8, 16), jnp.float32)
  moe_e = MoEMLP(cfg, impl="einsum")
  v = moe_e.init(jax.random.PRNGKey(0), x)
  out_e, st_e = moe_e.apply(v, x, mutable=["losses"])
  out_a, st_a = MoEMLP(cfg, impl="a2a").apply(v, x, mutable=["losses"])
  np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_a),
                             rtol=1e-4, atol=1e-6)
  # Aux loss must use GLOBAL routing statistics (pmean the fractions
  # before the product), matching the einsum path exactly.
  aux_e = jax.tree_util.tree_leaves(st_e["losses"])[0]
  aux_a = jax.tree_util.tree_leaves(st_a["losses"])[0]
  np.testing.assert_allclose(float(aux_e), float(aux_a), rtol=1e-5)

  def loss(params, impl):
    y, _ = MoEMLP(cfg, impl=impl).apply({"params": params}, x,
                                        mutable=["losses"])
    return jnp.sum(y ** 2)

  g_e = jax.jit(jax.grad(lambda p: loss(p, "einsum")))(v["params"])
  g_a = jax.jit(jax.grad(lambda p: loss(p, "a2a")))(v["params"])
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=1e-3, atol=1e-5),
      g_e, g_a)


def test_moe_a2a_gpt_trains():
  """GPT with moe_impl='a2a' trains end-to-end on the expert mesh with
  the batch sharded over (data, expert) — the EP regime the a2a
  dispatch exists for — and the lowered program contains real
  all-to-all collectives."""
  from jax.sharding import PartitionSpec as P

  env = epl.init()
  mesh = env.cluster.build_mesh(expert=4)
  cfg = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=16,
                  d_ff=32, max_seq_len=8, dtype=jnp.float32,
                  num_experts=4, moe_every=2, moe_impl="a2a",
                  capacity_factor=2.0)
  model = GPT(cfg)
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 9)),
                    jnp.int32)

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, ids[:, :-1])["params"],
                             tx=optax.adam(1e-2))

  state, shardings = create_sharded_train_state(init_fn, mesh,
                                                jax.random.PRNGKey(0))
  step = parallelize(
      make_train_step(lambda p, b, r: gpt_loss(model, p, b, r)),
      mesh, shardings, batch_spec=P(("data", "expert")))
  hlo = step.jitted.lower(state, {"ids": ids},
                          jax.random.PRNGKey(1)).compile().as_text()
  assert " all-to-all(" in hlo
  losses = []
  for i in range(4):
    state, m = step(state, {"ids": ids}, jax.random.PRNGKey(i))
    losses.append(float(m["loss"]))
  assert all(np.isfinite(l) for l in losses)
  assert losses[-1] < losses[0]
