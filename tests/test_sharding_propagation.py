"""Regression guards: tensor-parallel constraints must not destroy the
data sharding of batch dims (UNCONSTRAINED vs None in PartitionSpecs)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu import ops
from easyparallellibrary_tpu.ops.bridging import (
    replica_to_split, split_to_replica)
from easyparallellibrary_tpu.ops.losses import (
    distributed_sparse_softmax_cross_entropy_with_logits)


def _mesh():
  env = epl.init(epl.Config({"cluster.mesh_shape": "data:4,model:2"}))
  return epl.current_plan().build_mesh()


def _data_sharded(mesh, x, spec):
  return jax.device_put(x, NamedSharding(mesh, spec))


def test_ce_keeps_batch_sharding():
  mesh = _mesh()
  logits = _data_sharded(mesh, jnp.ones((8, 16, 32)), P("data", None, None))
  labels = _data_sharded(mesh, jnp.zeros((8, 16), jnp.int32),
                         P("data", None))

  @jax.jit
  def f(lg, lb):
    return distributed_sparse_softmax_cross_entropy_with_logits(lb, lg)

  out = f(logits, labels)
  # Per-example loss stays sharded over data — the constraint inside CE
  # must not have forced a gather of the batch dim.
  assert "data" in str(out.sharding.spec)


def test_bridging_keeps_batch_sharding():
  mesh = _mesh()
  x = _data_sharded(mesh, jnp.ones((8, 32)), P("data", None))
  y = jax.jit(replica_to_split)(x)
  spec = y.sharding.spec
  assert "data" in str(spec) and "model" in str(spec)
  z = jax.jit(split_to_replica)(y)
  assert "data" in str(z.sharding.spec)
  assert "model" not in str(z.sharding.spec[-1:])


def test_column_dense_keeps_batch_sharding():
  mesh = _mesh()
  model = ops.Dense(16, parallel="column")
  x = jnp.ones((8, 8))
  params = jax.jit(lambda: model.init(jax.random.PRNGKey(0), x))()["params"]
  xs = _data_sharded(mesh, x, P("data", None))
  out = jax.jit(lambda p, v: model.apply({"params": p}, v))(params, xs)
  spec = str(out.sharding.spec)
  assert "model" in spec      # feature dim sharded
  assert "data" in spec       # batch dim NOT gathered
