"""Self-healing fleet (ISSUE 13): SLO-driven actuators — the engine
autotuner (serving/autotune.py) and the fleet autoscaler
(serving/autoscale.py) — closing the sense->act control loop.

The acceptance contract (`make chaos-heal`): under an injected 3x
overload burst on a 2-replica process-transport fleet, the autoscaler
spawns a third replica (a REAL subprocess), the autotuner tightens
budgets, SLO burn recovers without operator input, every non-shed
request's output is bit-exact vs the fault-free oracle, all replica
compile counts stay 1, and after recovery the fleet drains back to 2
live replicas.  The quick-marked fault-free-equivalence test pins the
other half: actuators enabled with no breaches is bit-identical to the
baseline stream with zero actuations and zero added recompiles.  The
policy units (ladder, matching, cooldowns, flap breaker) drive pure
host objects with fake clocks.
"""

import json
import os
import time

import numpy as np
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.observability import slo as slo_lib
from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.observability.registry import MetricRegistry
from easyparallellibrary_tpu.observability.slo import (
    BurnRateRule, SLOMonitor, SLORule)
from easyparallellibrary_tpu.serving import Request, Router
from easyparallellibrary_tpu.serving.autoscale import FleetAutoscaler
from easyparallellibrary_tpu.serving.autotune import (
    TUNE_LEVELS, EngineAutotuner)
from easyparallellibrary_tpu.serving.resilience import AdmissionController
from easyparallellibrary_tpu.serving.scheduler import FCFSScheduler
from easyparallellibrary_tpu.testing.chaos import overload_burst
from easyparallellibrary_tpu.testing.factories import tiny_gpt

FACTORY = "easyparallellibrary_tpu.testing.factories:tiny_gpt"


@pytest.fixture(autouse=True)
def _drop_ambient_observability():
  yield
  trace_lib.reset()
  slo_lib.reset()


def _prompts(n, lengths=(5, 3, 7, 2), vocab=64, seed=0):
  r = np.random.RandomState(seed)
  return [r.randint(0, vocab, (lengths[i % len(lengths)],)).astype(
      np.int32) for i in range(n)]


def _oracle(model, params, prompt, max_new):
  import jax.numpy as jnp
  from easyparallellibrary_tpu.models.gpt import generate
  return np.asarray(
      generate(model, params, jnp.asarray(prompt)[None], max_new))[0]


# ------------------------------------------------------- config & trace


def test_autotune_autoscale_config_validation():
  with pytest.raises(ValueError, match="hold_steps"):
    epl.Config({"serving": {"autotune": {"hold_steps": 0}}})
  with pytest.raises(ValueError, match="max_level"):
    epl.Config({"serving": {"autotune": {"max_level": 9}}})
  with pytest.raises(ValueError, match="budget_chunks"):
    epl.Config({"serving": {"autotune": {"budget_chunks": 0}}})
  with pytest.raises(ValueError, match="min_replicas"):
    epl.Config({"serving": {"autoscale": {"min_replicas": 3,
                                          "max_replicas": 2}}})
  with pytest.raises(ValueError, match="scale_up_cooldown_s"):
    epl.Config({"serving": {"autoscale": {"scale_up_cooldown_s": -1.0}}})
  conf = epl.Config({"serving": {"autoscale": {"rules": "ttft_p99"}}})
  assert conf.serving.autoscale.rules == ("ttft_p99",)


def test_overload_burst_trace_shape():
  arr = overload_burst(10.0, 8, 4, factor=3.0, seed=0)
  assert arr.shape == (12,)
  assert np.all(np.diff(arr) >= 0) and arr[0] == 0.0
  # The burst segment arrives ~factor x faster than the recovery tail.
  burst_rate = 7 / max(arr[7] - arr[0], 1e-9)
  tail_rate = 4 / max(arr[11] - arr[7], 1e-9)
  assert burst_rate > tail_rate
  with pytest.raises(ValueError, match="factor"):
    overload_burst(10.0, 4, 2, factor=1.0)


# -------------------------------------------------- autotuner (policy)


class _FakeEngine:
  """Duck-typed engine for pure ladder-policy tests: a REAL scheduler
  and admission controller behind the attributes the tuner reads."""

  def __init__(self, spec_k=4, num_slots=4, queue_limit=8):
    self.scheduler = FCFSScheduler(num_slots=num_slots, prefill_chunk=8,
                                   max_seq_len=32, spec_k=spec_k)
    self.chunk = 8
    self._admission = AdmissionController(queue_limit=queue_limit)
    self._twin_label = "serving/fused_step"
    self._track_prefix = "serving"


def _burn_monitor(**kw):
  kw.setdefault("objective", 0.5)
  kw.setdefault("fast_window", 2)
  kw.setdefault("slow_window", 3)
  kw.setdefault("fast_burn", 1.0)
  kw.setdefault("slow_burn", 1.0)
  return SLOMonitor([BurnRateRule("shed_burn", bad="shed",
                                  good="finished_requests", **kw)])


class _BurnFeed:
  """Monotone cumulative shed/finished counters fed to a monitor — the
  well-formed stream a real engine produces (counters never run
  backwards, so burn deltas stay meaningful)."""

  def __init__(self, mon):
    self.mon = mon
    self.i = 0
    self.shed = 0.0
    self.good = 1.0

  def drive(self, records, shed_step=5.0, good_step=0.0):
    for _ in range(records):
      self.shed += shed_step
      self.good += good_step
      self.mon.observe(self.i, {
          "serving/shed": self.shed,
          "serving/finished_requests": self.good})
      self.i += 1


def test_autotuner_escalates_sustains_and_recovers():
  cfg = epl.Config({"serving": {"autotune": {"enabled": True,
                                             "hold_steps": 5}}})
  mon = _burn_monitor()
  eng = _FakeEngine()
  tuner = EngineAutotuner(eng, mon, config=cfg)
  feed = _BurnFeed(mon)
  # No breach -> level stays 0 and no knob moves.
  tuner.on_step(0)
  assert tuner.level == 0 and eng.scheduler.tune_spec_k == -1
  feed.drive(5)
  assert mon.breaches == 1 and tuner.breaches_heard == 1
  tuner.on_step(0)
  assert TUNE_LEVELS[tuner.level] == "spec_trim"
  assert eng.scheduler.tune_spec_k == 2          # half of k=4
  assert eng.scheduler.effective_spec_k == 2
  # Sustained pressure (stream stays breached, no new event): one more
  # level per hold window, through budget_tight up to slot_cap.
  for s in range(1, 20):
    tuner.on_step(s)
  assert TUNE_LEVELS[tuner.level] == "slot_cap"
  assert eng.scheduler.tune_spec_k == 0
  assert eng.scheduler.tune_budget == eng.chunk
  assert eng.scheduler.tune_slot_cap == 2        # half of 4, floor 1
  assert eng.scheduler.effective_max_batch == 2
  assert eng._admission.floor_level == 1
  # The admission ladder cannot de-escalate below the pinned floor.
  assert eng._admission.observe(0, 0.0) == 1
  # Burn recovers -> staged release, one level per hold window, back
  # to baseline with every clamp gone.
  feed.drive(4, shed_step=0.0, good_step=10.0)
  assert mon.breached_streams() == []
  for s in range(20, 60):
    tuner.on_step(s)
  assert tuner.level == 0
  assert eng.scheduler.tune_spec_k == -1
  assert eng.scheduler.tune_budget == 0
  assert eng.scheduler.tune_slot_cap == 0
  assert eng._admission.floor_level == 0
  assert tuner.actuations == 6                   # 3 up + 3 down
  assert mon.actuations == 6                     # jsonl-stream parity


def test_autotuner_live_sustained_breach_never_goes_stale():
  """The stale escape must key off RECORDS stopping, not breach-event
  age: a genuinely sustained overload (records flowing, stream stays
  breached, no transition events) holds mitigation indefinitely —
  releasing it mid-burn and never re-escalating would be the bug."""
  cfg = epl.Config({"serving": {"autotune": {"enabled": True,
                                             "hold_steps": 2,
                                             "max_level": 1}}})
  mon = _burn_monitor()
  eng = _FakeEngine()
  tuner = EngineAutotuner(eng, mon, config=cfg)
  feed = _BurnFeed(mon)
  feed.drive(5)
  tuner.on_step(0)
  assert tuner.level == 1
  for s in range(1, 2 * tuner.stale_steps + 5):
    feed.drive(1)            # overload continues: records keep flowing
    tuner.on_step(s)
  assert tuner.level == 1, \
      "live sustained breach was released as stale mid-overload"
  assert mon.breached_streams(), "the stream should still be breached"


def test_autotuner_stale_breach_cannot_pin():
  """A breach stream wedged 'breached' whose records stopped flowing
  (idle engine: burn windows see no traffic, so the stream never emits
  a recovery) goes stale and the tuner still climbs down."""
  cfg = epl.Config({"serving": {"autotune": {"enabled": True,
                                             "hold_steps": 2,
                                             "max_level": 1}}})
  mon = _burn_monitor()
  eng = _FakeEngine()
  tuner = EngineAutotuner(eng, mon, config=cfg)
  _BurnFeed(mon).drive(5)
  tuner.on_step(0)
  assert tuner.level == 1
  assert mon.breached_streams()                  # wedged breached
  for s in range(1, tuner.stale_steps + 5):
    tuner.on_step(s)
  assert tuner.level == 0, "stale breach pinned the engine slow"


def test_autotuner_spec_trim_floors_at_one_draft():
  """spec_trim trims, it does not shut off: a k=1 drafter keeps its
  one draft at level 1 (full spec-off is level 2's job); with no
  drafter (k=0) the clamp stays a no-op."""
  cfg = epl.Config({"serving": {"autotune": {"enabled": True}}})
  tuner = EngineAutotuner(_FakeEngine(spec_k=1), None, config=cfg)
  assert tuner._level_knobs(1)["tune_spec_k"] == 1
  assert tuner._level_knobs(2)["tune_spec_k"] == 0
  no_drafter = EngineAutotuner(_FakeEngine(spec_k=0), None, config=cfg)
  assert no_drafter._level_knobs(1)["tune_spec_k"] == 0


def test_autotuner_matching_scopes_breaches():
  cfg = epl.Config({"serving": {"autotune": {"enabled": True}}})
  eng = _FakeEngine()
  eng._track_prefix = "serving/replica0"
  eng._twin_label = "serving/replica0/fused_step"
  tuner = EngineAutotuner(eng, None, config=cfg)
  assert tuner._matches({"metric": "serving/replica0/ttft_p99_s"})
  assert tuner._matches({"metric": "serving/itl_p99_s"})
  assert tuner._matches({"twin": "serving/replica0/fused_step"})
  assert not tuner._matches({"metric": "serving/replica1/ttft_p99_s"})
  assert not tuner._matches({"metric": "serving/fleet/ttft_p99_s"})
  assert not tuner._matches({"twin": "serving/replica1/fused_step"})
  assert not tuner._matches({"metric": "train/loss"})
  assert not tuner._matches({})
  # A BARE engine (prefix "serving") must not swallow fleet- or
  # replica-scoped streams — the fleet is the autoscaler's to act on,
  # and a sibling replica's breach is not this engine's.
  bare = EngineAutotuner(_FakeEngine(), None, config=cfg)
  assert bare._matches({"metric": "serving/ttft_p99_s"})
  assert not bare._matches({"metric": "serving/fleet/ttft_p99_s"})
  assert not bare._matches({"metric": "serving/replica1/ttft_p99_s"})


# ------------------------------------------------- autoscaler (policy)


class FakeClock:
  def __init__(self, t=0.0):
    self.t = t

  def __call__(self):
    return self.t

  def advance(self, dt):
    self.t += dt


class FakeReplica:
  def __init__(self, index):
    self.index = index
    self.finished = {}
    self.has_work = False
    self.num_slots = 4
    self.stats = None
    self.watchdog_timeouts = 0
    self.bad_steps = 0
    self.itl_ewma_s = 0.0

  load = property(lambda self: 0)
  queue_depth = property(lambda self: 0)
  num_active = property(lambda self: 0)

  def submit(self, req):
    return True

  def cancel(self, uid):
    return False

  def step(self):
    return []

  def evacuate(self):
    return []

  def restore_request(self, snap, front=False):
    return snap["request"]["uid"]

  def close(self):
    pass


def _scaling_router(clock, monitor=None, **autoscale):
  autoscale.setdefault("enabled", True)
  autoscale.setdefault("min_replicas", 2)
  autoscale.setdefault("max_replicas", 4)
  autoscale.setdefault("scale_up_cooldown_s", 1.0)
  autoscale.setdefault("scale_down_cooldown_s", 10.0)
  autoscale.setdefault("flap_window_s", 30.0)
  config = epl.Config({"serving": {"autoscale": autoscale}})
  if monitor is not None:
    slo_lib.install(monitor)   # explicit install wins; Router binds it
  router = Router(replicas=[FakeReplica(0), FakeReplica(1)],
                  config=config, clock=clock)
  # Injected fleets carry no build recipe; grow with fakes instead.
  def add_replica():
    index = len(router.replicas)
    router.replicas.append(FakeReplica(index))
    router.health.append(router._make_health(index))
    return index
  router.add_replica = add_replica
  return router, router._autoscaler


def _burn_breach(scaler, rule="shed_burn"):
  """Deliver one burn-rate breach exactly as the monitor would (the
  listener path; end-to-end monitor wiring is covered by the quick and
  slow episodes below)."""
  scaler._on_breach(rule, {"metric": "serving/fleet/shed",
                           "fast_burn": 4.0, "slow_burn": 2.0})


def test_autoscaler_scales_up_on_burn_and_drains_after_quiet():
  clock = FakeClock()
  router, scaler = _scaling_router(clock)
  router.step()
  assert len(router.replicas) == 2 and scaler.scale_ups == 0
  # A threshold rule NOT named in autoscale.rules is ignored.
  scaler._on_breach("ttft_p99", {"metric": "serving/fleet/ttft_p99_s",
                                 "value": 9.0, "target": 0.5})
  router.step()
  assert scaler.scale_ups == 0
  _burn_breach(scaler)
  router.step()                       # actuation lands at sweep start
  assert scaler.scale_ups == 1 and len(router.replicas) == 3
  assert scaler._added == [2]
  assert router.states() == ["healthy", "healthy", "healthy"]
  counters = router.router_counters()
  assert counters["scale_ups"] == 1.0 and counters["scale_downs"] == 0.0
  # A second burn inside the scale-up cooldown is held...
  clock.advance(0.5)
  _burn_breach(scaler)
  router.step()
  assert scaler.scale_ups == 1 and scaler.holds == 1
  # ...past it, the fleet grows again, up to the max_replicas bound.
  clock.advance(1.0)
  _burn_breach(scaler)
  router.step()
  assert scaler.scale_ups == 2 and len(router.replicas) == 4
  clock.advance(1.5)
  _burn_breach(scaler)
  router.step()
  assert len(router.replicas) == 4 and scaler.holds == 2
  # Budget recovered -> after the quiet cooldown the youngest-added
  # replicas drain back out, one per sweep — but never capacity the
  # autoscaler did not add.
  clock.advance(100.0)
  router.step()
  assert scaler.scale_downs == 1
  assert router.states()[3] == "draining"
  clock.advance(100.0)
  router.step()
  assert scaler.scale_downs == 2
  assert router.states() == ["healthy", "healthy", "draining",
                             "draining"]
  clock.advance(100.0)
  router.step()                       # nothing added left: no shrink
  assert scaler.scale_downs == 2
  assert [h.state for h in router.health[:2]] == ["healthy", "healthy"]


def test_autoscaler_named_threshold_rule_scales():
  clock = FakeClock()
  router, scaler = _scaling_router(clock, rules="ttft_p99")
  scaler._on_breach("ttft_p99", {"metric": "serving/fleet/ttft_p99_s",
                                 "value": 9.0, "target": 0.5})
  router.step()
  assert scaler.scale_ups == 1 and len(router.replicas) == 3


def test_autoscaler_rejoins_only_its_own_drained_capacity():
  """Warm rejoin targets only replicas the AUTOSCALER drained; an
  operator-drained replica is maintenance in progress and is never
  silently reverted by a breach — the fleet grows by cold spawn
  instead."""
  clock = FakeClock()
  # min_replicas=1: the operator drain already takes live to 2, and
  # phase two needs headroom for the autoscaler's own shrink.
  router, scaler = _scaling_router(clock, min_replicas=1,
                                   scale_down_cooldown_s=5.0)
  router.drain(1)                     # OPERATOR maintenance drain
  assert router.states() == ["healthy", "draining"]
  _burn_breach(scaler)
  router.step()
  assert scaler.scale_ups == 1
  assert len(router.replicas) == 3, "operator drain must not revert"
  assert router.states() == ["healthy", "draining", "healthy"]
  # The autoscaler's OWN drained capacity IS the warm-rejoin target.
  clock.advance(50.0)
  router.step()                       # quiet -> drains its replica 2
  assert scaler.scale_downs == 1 and scaler._parked == [2]
  clock.advance(2.0)
  _burn_breach(scaler)
  router.step()
  assert scaler.scale_ups == 2
  assert len(router.replicas) == 3, "warm rejoin, not another spawn"
  assert router.states() == ["healthy", "draining", "healthy"]
  assert scaler._parked == [] and 2 in scaler._added
  # A parked claim dies the moment the replica leaves draining through
  # a NON-autoscaler path: operator rejoins 2, later drains it for
  # maintenance — a breach must now spawn, never revert that drain.
  clock.advance(50.0)
  router.step()                       # quiet -> autoscaler parks 2
  assert scaler._parked == [2]
  router.rejoin(2)                    # operator takes it back...
  router.step()                       # ...claim pruned this sweep
  assert scaler._parked == []
  router.drain(2)                     # operator maintenance drain
  clock.advance(2.0)
  _burn_breach(scaler)
  router.step()
  assert len(router.replicas) == 4, "operator drain was reverted"
  assert router.health[2].state == "draining"


def test_autoscaler_never_drains_operator_base_capacity():
  """Shrink touches ONLY capacity the autoscaler added: if its spawned
  replica has since died, the operator's base fleet is not a fallback
  drain target."""
  clock = FakeClock()
  router, scaler = _scaling_router(clock)
  _burn_breach(scaler)
  router.step()
  assert scaler._added == [2]
  router.health[2].mark_down("chaos: added capacity died")
  clock.advance(100.0)
  router.step()
  assert scaler.scale_downs == 0
  assert [h.state for h in router.health[:2]] == ["healthy", "healthy"]


def test_autoscaler_live_burn_sustains_growth_and_blocks_shrink():
  """A burn that records keep confirming (stream breached, counts
  growing) sustains growth past the first cooldown AND holds the quiet
  window open indefinitely — only once its records STOP flowing does
  the stale escape let the fleet shrink."""
  clock = FakeClock()
  monitor = _burn_monitor()
  router, scaler = _scaling_router(clock, monitor=monitor)
  assert router._slo is monitor
  feed_i = [0]

  def burn(shed):
    monitor.observe(feed_i[0], {
        "serving/custom/shed": float(shed),
        "serving/custom/finished_requests": 1.0})
    feed_i[0] += 1

  total = [0.0]
  for _ in range(5):
    total[0] += 5.0
    burn(total[0])
  assert monitor.breaches == 1
  router.step()
  assert scaler.scale_ups == 1 and len(router.replicas) == 3
  # Records keep flowing: growth continues after the hold-out...
  clock.advance(1.2)
  total[0] += 5.0
  burn(total[0])
  router.step()
  assert scaler.scale_ups == 2 and len(router.replicas) == 4
  # ...and the shrink stays blocked FAR past the quiet cooldown.
  for _ in range(12):
    clock.advance(3.0)
    total[0] += 5.0
    burn(total[0])
    router.step()
  assert scaler.scale_downs == 0, "live burn was read as recovered"
  # Records stop (stream wedges breached): the stale escape opens the
  # quiet window and the added capacity drains back out.
  clock.advance(100.0)
  router.step()
  assert scaler.scale_downs == 1
  assert router.states()[3] == "draining"


def test_autoscaler_flap_breaker_doubles_holdout():
  clock = FakeClock()
  router, scaler = _scaling_router(
      clock, scale_down_cooldown_s=5.0, flap_window_s=30.0)
  base = scaler.scale_up_cooldown_s
  _burn_breach(scaler)
  router.step()
  assert scaler.scale_ups == 1 and scaler.flap_trips == 0
  # Quiet -> drain -> breach again INSIDE the flap window: the re-grow
  # counts a trip and the next hold-out doubles.
  clock.advance(6.0)
  router.step()
  assert scaler.scale_downs == 1
  clock.advance(2.0)
  _burn_breach(scaler)
  router.step()
  assert scaler.scale_ups == 2 and scaler.flap_trips == 1
  assert scaler.scale_up_holdout_s() == pytest.approx(2 * base)
  # A breach inside the DOUBLED hold-out is held, not acted on.
  clock.advance(1.2)
  _burn_breach(scaler)
  router.step()
  assert scaler.scale_ups == 2 and scaler.holds >= 1
  # A clean flap window decays the trip again.
  clock.advance(31.0)
  router.step()
  assert scaler.flap_trips == 0


def _wait_spawn_outcome(scaler, timeout=5.0):
  deadline = time.monotonic() + timeout
  while time.monotonic() < deadline:
    with scaler._lock:
      if scaler._spawn_outcome is not None:
        return True
    time.sleep(0.005)
  return False


def test_autoscaler_cold_spawn_off_thread_slow_fake(monkeypatch):
  """ROADMAP item 5 leftover closed: a cold scale-up spawn runs OFF the
  synchronous sweep thread.  With a SLOW fake spawn in flight the sweep
  keeps returning immediately, the new replica is unroutable until the
  outcome is adopted at a later sweep, repeat grow impulses hold, and a
  failing spawn books spawn_failures without ever counting a flap."""
  import threading

  clock = FakeClock()
  router, scaler = _scaling_router(clock)
  release = threading.Event()

  def slow_build(index=None):
    # The slow fake spawn: blocks until the test releases it — exactly
    # the window a real subprocess spawn + in-child compile occupies.
    assert release.wait(timeout=10.0)
    return FakeReplica(len(router.replicas))

  router.build_replica = slow_build
  router._replica_spec = {}        # recipe "available": async path on
  assert router.spawn_recipe_available
  _burn_breach(scaler)
  t0 = time.monotonic()
  router.step()                    # starts the spawn, does NOT block
  assert time.monotonic() - t0 < 1.0, "sweep blocked on the cold spawn"
  assert scaler.spawn_in_flight
  assert len(router.replicas) == 2, "replica routable before ready"
  assert scaler.scale_ups == 0
  # Repeat grow impulses during the in-flight spawn hold, never stack.
  _burn_breach(scaler)
  router.step()
  assert scaler.scale_ups == 0 and scaler.holds >= 1
  assert len(router.replicas) == 2
  # Release the spawn; the outcome lands at the NEXT sweep boundary.
  release.set()
  assert _wait_spawn_outcome(scaler), "spawn outcome never posted"
  assert len(router.replicas) == 2, "adoption must wait for the sweep"
  router.step()
  assert scaler.scale_ups == 1 and len(router.replicas) == 3
  assert not scaler.spawn_in_flight
  assert 2 in scaler._added
  assert router.states() == ["healthy", "healthy", "healthy"]
  assert scaler.flap_trips == 0
  # Failure half: a raising spawn is booked and cooled down, and is
  # NEVER a flap even right after a scale-down (no grow landed).
  clock.advance(100.0)
  router.step()                    # quiet -> drains replica 2
  assert scaler.scale_downs == 1

  def bad_build(index=None):
    raise RuntimeError("fake spawn failure")

  router.build_replica = bad_build
  scaler._parked = []              # force the cold-spawn path, not rejoin
  clock.advance(2.0)               # inside flap_window_s of the drain
  _burn_breach(scaler)
  router.step()                    # starts (and fails) the spawn
  assert _wait_spawn_outcome(scaler), "failure outcome never posted"
  router.step()                    # books the failure
  assert scaler.spawn_failures == 1
  assert scaler.flap_trips == 0, "a failed spawn must not count a flap"
  assert scaler.scale_ups == 1 and len(router.replicas) == 3


# --------------------------------------- quick: fault-free equivalence


@pytest.mark.quick
def test_actuators_fault_free_bit_exact_zero_actuations():
  """The fault-free guard (ISSUE 13 satellite): autotuner + autoscaler
  + SLO monitor enabled with NO breaches is bit-identical to the
  baseline fleet stream — zero actuations fire, every engine's fused
  step compiles once, and the monitor stays silent."""
  prompts = _prompts(4)
  max_new = (6, 7, 4, 5)

  def drive(router):
    out = {}
    for i in range(2):
      assert router.submit(Request(uid=i, prompt=prompts[i],
                                   max_new_tokens=max_new[i]))
    for _ in range(2):
      for fin in router.step():
        out[fin.uid] = fin.tokens
    for i in range(2, 4):
      assert router.submit(Request(uid=i, prompt=prompts[i],
                                   max_new_tokens=max_new[i]))
    out.update(router.run())
    return out

  epl.init()
  model, params = tiny_gpt()
  base_router = Router(model, params, num_replicas=2, num_slots=2,
                       prefill_chunk=4, registry=MetricRegistry())
  base = drive(base_router)
  base_router.close()
  slo_lib.reset()

  config = epl.Config({
      "serving": {
          "resilience": {"enabled": True, "queue_limit": 16},
          "autotune": {"enabled": True, "hold_steps": 2},
          "autoscale": {"enabled": True, "min_replicas": 2,
                        "max_replicas": 4,
                        "scale_up_cooldown_s": 0.0,
                        "scale_down_cooldown_s": 0.5},
      },
      "observability": {"slo": {
          "enabled": True, "ttft_p99_s": 100.0, "itl_p99_s": 100.0,
          "shed_objective": 0.5, "fast_window": 2, "slow_window": 3,
          "fast_burn": 1.0, "slow_burn": 1.0}},
  })
  epl.init(config)
  router = Router(model, params, num_replicas=2, config=config,
                  num_slots=2, prefill_chunk=4,
                  registry=MetricRegistry())
  healed = drive(router)
  monitor = slo_lib.get_monitor()
  assert monitor is not None and monitor.breaches == 0
  assert monitor.actuations == 0
  assert router._autoscaler is not None
  assert router._autoscaler.counters() == {
      "scale_ups": 0.0, "scale_downs": 0.0, "autoscale_holds": 0.0,
      "flap_trips": 0.0, "predictive_fires": 0.0}
  assert len(router.replicas) == 2
  for rep in router.replicas:
    tuner = rep.engine._autotuner
    assert tuner is not None and tuner.actuations == 0
    assert tuner.level == 0
    assert rep.engine._step_fn._cache_size() == 1
    assert rep.engine._compile_sentinel.recompiles == 0
  assert sorted(base) == sorted(healed)
  for uid in base:
    np.testing.assert_array_equal(healed[uid], base[uid],
                                  err_msg=f"req {uid}")
  # The per-step serving records carry the actuator evidence keys.
  latest = router.replicas[0].engine.registry.latest()
  assert latest["serving/replica0/autotune_level"] == 0
  assert latest["serving/replica0/autotune_actuations"] == 0
  router.close()


# ------------------------------------ slow: the chaos-heal acceptance


@pytest.mark.slow
def test_overload_burst_heals_scales_and_drains_back(tmp_path):
  """`make chaos-heal` acceptance (ISSUE 13): a 3x overload burst on a
  2-replica PROCESS-transport fleet — the autoscaler spawns a third
  replica (real subprocess), at least one engine autotuner tightens
  its knobs, the burn recovers with no operator input, every non-shed
  request is bit-exact vs the fault-free oracle, all replica compile
  counts stay 1, and after recovery the fleet drains back to 2 live
  replicas."""
  events_path = str(tmp_path / "slo_events.jsonl")
  config = epl.Config({
      "serving": {
          "resilience": {"enabled": True, "queue_limit": 3},
          "router": {"transport": "process", "heartbeat_s": 0.02},
          "autotune": {"enabled": True, "hold_steps": 8},
          "autoscale": {"enabled": True, "min_replicas": 2,
                        "max_replicas": 3,
                        "scale_up_cooldown_s": 0.2,
                        "scale_down_cooldown_s": 1.5,
                        "flap_window_s": 10.0},
      },
      "observability": {"slo": {
          "enabled": True, "events_path": events_path,
          "shed_objective": 0.5, "fast_window": 2, "slow_window": 4,
          "fast_burn": 1.0, "slow_burn": 1.0}},
  })
  epl.init(config)
  model, params = tiny_gpt()          # the parent-side oracle twin
  router = Router(num_replicas=2, config=config, factory=FACTORY,
                  num_slots=2, prefill_chunk=4)
  prompts = _prompts(20, seed=3)
  max_new = 6
  accepted, shed = [], []
  # 3x overload burst, waves interleaved with sweeps so the shed
  # counter GROWS across successive fleet rollups (a burn window needs
  # deltas, not one spike before the first record).
  uid = 0
  for _wave in range(5):
    for _ in range(4):
      if router.submit(Request(uid=uid, prompt=prompts[uid],
                               max_new_tokens=max_new)):
        accepted.append(uid)
      else:
        shed.append(uid)
      uid += 1
    for _ in range(3):
      router.step()
      time.sleep(0.02)               # let heartbeat rollups publish
  assert shed, "the burst must overload admission (nothing shed?)"
  # Serve the backlog; the breach + scale-up land mid-drive.  The
  # moment the third replica exists, a post-wave goes through it (its
  # load gauge is zero while the survivors still hold the backlog, so
  # least-loaded dispatch picks it) — the added capacity must SERVE,
  # not idle.
  post, post_placed = [], []
  post_prompts = _prompts(6, seed=11)   # fresh: no prefix affinity,
  deadline = time.monotonic() + 120.0   # so least-loaded wins and the
  scaler = router._autoscaler           # idle new replica is chosen
  filler_uid = 500
  while time.monotonic() < deadline:
    router.step()
    if scaler.scale_ups >= 1 and not post and router.has_work:
      for k in range(6):
        uid = 100 + k
        if router.submit(Request(uid=uid, prompt=post_prompts[k],
                                 max_new_tokens=max_new)):
          post.append(uid)
          post_placed.append(router.placement.get(uid))
    if not router.has_work:
      if post or (not scaler.spawn_in_flight
                  and scaler.scale_ups == 0):
        break
      # The cold spawn now runs OFF the sweep thread (ROADMAP item 5
      # leftover closed): the backlog can drain before the child is
      # ready, so keep light pressure on the survivors until adoption
      # lands AND the post wave is submitted — the post wave must meet
      # a loaded fleet with one idle fresh replica, which is the
      # scenario being pinned.
      if router.submit(Request(uid=filler_uid,
                               prompt=prompts[(filler_uid - 500) % 20],
                               max_new_tokens=max_new)):
        accepted.append(filler_uid)
      filler_uid += 1
  assert scaler.scale_ups >= 1, "no scale-up fired"
  assert len(router.replicas) == 3
  spawned = router.replicas[2]
  assert spawned.child_pid is not None and spawned.last_spawn_s > 0
  assert 2 in post_placed, "the spawned replica never received work"
  # Recovery tail: light traffic keeps rollups flowing with zero new
  # sheds, so the burn recovers and the quiet cooldown elapses.
  monitor = slo_lib.get_monitor()
  tail_uid = 1000
  deadline = time.monotonic() + 60.0
  while time.monotonic() < deadline:
    if not router.has_work:
      if scaler.scale_downs >= 1:
        break
      router.submit(Request(uid=tail_uid, prompt=prompts[0],
                            max_new_tokens=2))
      tail_uid += 1
    router.step()
    time.sleep(0.02)
  assert monitor.recoveries >= 1, "burn never recovered"
  assert scaler.scale_downs >= 1, "fleet never drained back down"
  live = [h.state for h in router.health
          if h.state in ("healthy", "suspect")]
  assert len(live) == 2
  assert router.health[2].state == "draining"
  # Compile-once fleet-wide: every child's beat-carried cache size is 1.
  for rep in router.replicas:
    assert rep.compile_count == 1, "actuation cost a recompile"
  # Bit-exactness for every non-shed request vs the oracle — the burst
  # wave AND the post-scale-up wave the spawned replica served.
  for u in accepted + post:
    fin = router.finished[u]
    if fin.finish_reason == "shed":  # replica-side admission shed
      continue
    assert fin.finish_reason == "length"
    if u >= 500:                        # spawn-window filler traffic
      prompt = prompts[(u - 500) % 20]
    elif u >= 100:
      prompt = post_prompts[u - 100]
    else:
      prompt = prompts[u]
    np.testing.assert_array_equal(
        fin.tokens, _oracle(model, params, prompt, max_new),
        err_msg=f"req {u}")
  router.close()
  # The events stream recorded the loop closing: autoscale actuations
  # from the parent, autotune actuations from at least one child.
  events = [json.loads(line) for line in open(events_path)]
  actuations = [e for e in events if e["event"] == "actuation"]
  assert any(e.get("actuator") == "autoscale" and
             e.get("action") == "scale_up" for e in actuations)
  assert any(e.get("actuator") == "autotune" for e in actuations), \
      "no child autotuner actuation reached slo_events.jsonl"
  assert any(e["event"] == "breach" for e in events)
  assert any(e["event"] == "recover" for e in events)
