"""Strategy scope / context / plan tests (reference analog:
tests/strategy_test.py, tests/strategy_context_test.py)."""

import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.env import Env


def test_replicate_scope_records_taskgraph():
  epl.init()
  with epl.replicate(1) as r:
    assert Env.get().strategy_context.current is r
  ctx = Env.get().strategy_context
  assert len(ctx.taskgraphs) == 1
  assert ctx.taskgraphs[0].kind == "replicate"
  assert ctx.current is None


def test_consecutive_replicates_become_stages():
  # Reference: consecutive named replicate scopes are pipeline stages
  # (epl/strategies/replicate.py).
  epl.init()
  with epl.replicate(1):
    pass
  with epl.replicate(1):
    pass
  plan = epl.current_plan()
  assert len(plan.replicate_taskgraphs) == 2
  assert plan.num_stages == 2
  assert plan.pipeline_enabled


def test_loop_reentry_reuses_taskgraph():
  # Re-entering the same `with` statement (layer loop / retrace) must not
  # mint a new stage (reference call-stack identity,
  # epl/strategies/parallel_strategy.py:48-57).
  epl.init()
  for _ in range(3):
    with epl.replicate(1):
      pass
  assert len(Env.get().strategy_context.taskgraphs) == 1


def test_split_records_model_parallel():
  epl.init()
  with epl.split(4):
    pass
  plan = epl.current_plan()
  assert plan.model_parallel == 4
  assert len(plan.split_taskgraphs) == 1


def test_nesting_rules():
  # Reference: epl/strategies/strategy_context.py:34-54.
  epl.init()
  with pytest.raises(ValueError):
    with epl.replicate(1):
      with epl.replicate(1):
        pass
  epl.init()
  with pytest.raises(ValueError):
    with epl.replicate(1):
      with epl.split(2):
        pass
  epl.init()
  with epl.split(2):
    with epl.split(2) as inner:   # nested split tolerated, marked nested
      assert inner.is_nested


def test_default_strategy():
  epl.init()
  epl.set_default_strategy(epl.replicate(1))
  ctx = Env.get().strategy_context
  assert ctx.current is not None
  assert ctx.current.kind == "replicate"
  assert len(ctx.taskgraphs) == 1


def test_plan_mesh_request_and_build():
  epl.init(epl.Config({"pipeline.num_micro_batch": 2}))
  with epl.replicate(1):
    pass
  with epl.replicate(1):
    pass
  plan = epl.current_plan()
  mesh = plan.build_mesh()
  sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
  assert sizes["stage"] == 2
  assert sizes["data"] == 4
  assert plan.num_micro_batch == 2
  # Taskgraphs got their virtual devices.
  assert all(t.virtual_device is not None for t in plan.replicate_taskgraphs)


def test_auto_parallel_stage_count_from_config():
  epl.init(epl.Config({"auto.auto_parallel": True,
                       "pipeline.num_stages": 4}))
  with epl.replicate(1):
    pass
  plan = epl.current_plan()
  assert plan.num_stages == 4


def test_device_count_validation():
  with pytest.raises(ValueError):
    epl.replicate(0)


def test_scope_reentry_as_binding_is_canonical():
  epl.init()
  seen = []
  for _ in range(2):
    with epl.replicate(1) as r:
      seen.append(r)
      r.taskgraph.add_param_prefix("blk")   # must not crash on re-entry
  assert seen[0] is seen[1]
  assert seen[0].taskgraph is not None


def test_mesh_shape_conflict_with_scopes_raises():
  epl.init(epl.Config({"cluster.mesh_shape": "data:8"}))
  with epl.replicate(1):
    pass
  with epl.replicate(1):
    pass
  with pytest.raises(ValueError):
    epl.current_plan().build_mesh()


def test_split_none_takes_whole_model_axis():
  epl.init()
  with epl.split():
    pass
  plan = epl.current_plan()
  assert plan.model_parallel == 8
  mesh = plan.build_mesh()
  assert dict(zip(mesh.axis_names, mesh.devices.shape))["model"] == 8


def test_named_scopes_in_loop_make_distinct_stages():
  epl.init()
  for i in range(3):
    with epl.replicate(1, name=f"stage{i}"):
      pass
  assert epl.current_plan().num_stages == 3
