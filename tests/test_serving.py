"""Serving subsystem: continuous-batching engine, slot cache, scheduler.

The exactness contract under test: the fused prefill+decode engine is a
pure REBATCHING of the legacy ``generate(use_cache=True)`` path — greedy
token ids are bit-identical per request, no matter when a request was
admitted, which slot served it, or who occupied that slot before
(ISSUE 3 acceptance).  ``generate`` stays the oracle.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.models.gpt import generate, sample_logits
from easyparallellibrary_tpu.profiler import ServingStats, percentile
from easyparallellibrary_tpu.serving import (
    ContinuousBatchingEngine, FCFSScheduler, Request, SlotAllocator,
    allocate_kv_cache, cache_length, sample_token_slots)

TINY = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                 d_ff=64, max_seq_len=32, dtype=jnp.float32)


def _model_and_params(cfg=TINY, seed=0):
  model = GPT(cfg)
  params = model.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, 4), jnp.int32))["params"]
  return model, params


def _prompts(lengths, vocab=64, seed=0):
  r = np.random.RandomState(seed)
  return [r.randint(0, vocab, (n,)).astype(np.int32) for n in lengths]


def _oracle(model, params, prompt, max_new):
  return np.asarray(
      generate(model, params, jnp.asarray(prompt)[None], max_new))[0]


# ---------------------------------------------------------------- exactness


@pytest.mark.quick
def test_engine_greedy_exact_vs_generate_staggered():
  """Greedy continuous batching is bit-exact vs generate(use_cache=True)
  per request — including requests admitted at different iterations and
  slots reused across retirements (num_slots < num requests)."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3, 9, 1, 6, 2))
  max_new = (6, 7, 8, 4, 5, 9)
  eng = ContinuousBatchingEngine(model, params, num_slots=3,
                                 prefill_chunk=4)
  # The whole serving drive runs under the device->host transfer guard:
  # the engine's ONE designated per-step fetch is explicit
  # (jax.device_get), so any IMPLICIT sync creeping into the hot loop —
  # a float()/np.asarray on a device value — fails here at runtime,
  # the complement of epl-lint's static host-sync rule
  # (docs/static_analysis.md).
  with jax.transfer_guard_device_to_host("disallow"):
    for i in range(3):
      eng.submit(Request(uid=i, prompt=prompts[i],
                         max_new_tokens=max_new[i]))
    out = {}
    for _ in range(2):  # second wave joins a mid-flight batch
      for fin in eng.step():
        out[fin.uid] = fin.tokens
    for i in range(3, len(prompts)):
      eng.submit(Request(uid=i, prompt=prompts[i],
                         max_new_tokens=max_new[i]))
    out.update(eng.run())
  assert sorted(out) == list(range(len(prompts)))
  for i, p in enumerate(prompts):
    np.testing.assert_array_equal(
        out[i], _oracle(model, params, p, max_new[i]), err_msg=f"req {i}")


@pytest.mark.quick
def test_engine_tp2_exact_vs_dense_generate():
  """The engine on a TP=2 virtual mesh (heads sharded over `model`, slot
  cache allocated sharded) reproduces the dense single-program oracle's
  greedy ids exactly."""
  import flax.linen as nn
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state)
  epl.init(epl.Config({"cluster.mesh_shape": "data:4,model:2"}))
  mesh = epl.Env.get().cluster.build_mesh()
  cfg = GPTConfig(**{**TINY.__dict__, "tensor_parallel": True})
  model = GPT(cfg)
  prompts = _prompts((4, 7, 2), seed=1)

  def init_fn(rng):
    return TrainState.create(
        apply_fn=model.apply,
        params=model.init(rng, jnp.asarray(prompts[0])[None])["params"],
        tx=optax.sgd(0.1))

  state, _ = create_sharded_train_state(init_fn, mesh,
                                        jax.random.PRNGKey(5))
  eng = ContinuousBatchingEngine(model, state.params, mesh=mesh,
                                 num_slots=2, prefill_chunk=4)
  # Sync-free hot loop on the TP mesh too (see the staggered test).
  with jax.transfer_guard_device_to_host("disallow"):
    for i, p in enumerate(prompts):
      eng.submit(Request(uid=i, prompt=p, max_new_tokens=5))
    out = eng.run()

  dense = GPT(TINY)
  host_params = jax.tree_util.tree_map(np.asarray,
                                       nn.meta.unbox(state.params))
  for i, p in enumerate(prompts):
    np.testing.assert_array_equal(
        out[i], _oracle(dense, host_params, p, 5), err_msg=f"req {i}")


@pytest.mark.quick
def test_slot_reuse_no_stale_kv_leakage():
  """Retire + readmit reuses the slot with no stale-KV leakage: a SHORT
  request served after a LONG one in the same (only) slot matches its
  from-scratch oracle bit-exactly — the long request's K/V tail is still
  physically in the cache but must never be attendable."""
  epl.init()
  model, params = _model_and_params(seed=2)
  long_p, short_p = _prompts((12, 3), seed=3)
  eng = ContinuousBatchingEngine(model, params, num_slots=1,
                                 prefill_chunk=4)
  # Slot reuse must stay sync-free as well (see the staggered test).
  with jax.transfer_guard_device_to_host("disallow"):
    eng.submit(Request(uid="long", prompt=long_p, max_new_tokens=10))
    out = eng.run()
    eng.submit(Request(uid="short", prompt=short_p, max_new_tokens=6))
    out.update(eng.run())
  np.testing.assert_array_equal(out["long"],
                                _oracle(model, params, long_p, 10))
  np.testing.assert_array_equal(out["short"],
                                _oracle(model, params, short_p, 6))


def test_stop_token_retires_early():
  """A request retires at its stop token (included in the output) —
  output equals the unconstrained greedy decode truncated at the stop
  token's first occurrence."""
  epl.init()
  model, params = _model_and_params()
  (prompt,) = _prompts((5,))
  plen = len(prompt)
  ref = _oracle(model, params, prompt, 4)
  gen_part = list(ref[plen:])
  stop = gen_part[1]  # appears at generated index <= 1
  cut = gen_part.index(stop)  # first occurrence decides retirement
  eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                 prefill_chunk=4)
  eng.submit(Request(uid="s", prompt=prompt, max_new_tokens=20,
                     stop_token=int(stop)))
  fins = []
  while eng.has_work:
    fins.extend(eng.step())
  assert len(fins) == 1 and fins[0].finish_reason == "stop_token"
  np.testing.assert_array_equal(fins[0].tokens, ref[:plen + cut + 1])


# --------------------------------------------------------------- throughput


@pytest.mark.quick
def test_continuous_batching_beats_sequential_static_batch():
  """ISSUE 3 acceptance: on the 8-device virtual CPU mesh with staggered
  arrivals and skewed decode lengths, continuous batching yields more
  useful tokens/s than sequential static-batch generate() calls — each
  static batch runs EVERY request to its batch's longest horizon (a
  whole-loop-fused program, so the baseline pays zero per-step host
  overhead), while the engine retires short requests and backfills their
  slots from the queue every iteration.

  The model is deliberately larger than TINY: the comparison is honest
  only where per-step compute, not dispatch, dominates — same reason
  benchmarks/decode_throughput.py uses this shape.
  """
  import time
  epl.init()
  cfg = GPTConfig(vocab_size=256, num_layers=4, num_heads=8, d_model=128,
                  d_ff=512, max_seq_len=128, dtype=jnp.float32)
  model, params = _model_and_params(cfg)
  B, plen, waves = 8, 8, 4
  wave_new = [48] + [8] * (B - 1)   # skew: one long request per wave
  max_new = wave_new * waves
  prompts = _prompts([plen] * (B * waves), vocab=256, seed=4)
  useful = sum(max_new)

  horizon = max(wave_new)
  gen = jax.jit(lambda p, ids: generate(model, p, ids, horizon))
  batches = [jnp.asarray(np.stack(prompts[w * B:(w + 1) * B]))
             for w in range(waves)]
  jax.block_until_ready(gen(params, batches[0]))  # warmup/compile
  t0 = time.perf_counter()
  base_out = [jax.block_until_ready(gen(params, b)) for b in batches]
  base_s = time.perf_counter() - t0
  base_tps = useful / base_s

  eng = ContinuousBatchingEngine(model, params, num_slots=B,
                                 prefill_chunk=1)
  eng.submit(Request(uid="warm", prompt=prompts[0], max_new_tokens=2))
  eng.run()  # compile outside the timed region, slots drain back free

  t0 = time.perf_counter()
  for w in range(waves):          # staggered: each wave joins mid-flight
    for i in range(w * B, (w + 1) * B):
      eng.submit(Request(uid=i, prompt=prompts[i],
                         max_new_tokens=max_new[i]))
    eng.step()
  out = eng.run()
  eng_s = time.perf_counter() - t0
  eng_tps = useful / eng_s

  # Exactness rides along: engine output == the baseline's own tokens
  # truncated to each request's budget.
  for i in range(B * waves):
    ref = np.asarray(base_out[i // B][i % B])[:plen + max_new[i]]
    np.testing.assert_array_equal(out[i], ref, err_msg=f"req {i}")
  assert eng_tps > base_tps, (
      f"continuous batching {eng_tps:.1f} tok/s did not beat sequential "
      f"static batches {base_tps:.1f} tok/s")


# ----------------------------------------------------------------- sampling


def test_per_request_rng_streams_slot_independent():
  """A request's sample stream depends only on its seed and token index
  — not on which slot or iteration serves it: the same workload sampled
  under different slot counts (different schedules) yields identical
  tokens, different seeds yield different tokens."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 5, 3), seed=6)
  prompts[1] = prompts[0].copy()  # identical prompt for the seed test

  def run(num_slots, seeds):
    eng = ContinuousBatchingEngine(model, params, num_slots=num_slots,
                                   prefill_chunk=4)
    for i, p in enumerate(prompts):
      eng.submit(Request(uid=i, prompt=p, max_new_tokens=8,
                         temperature=0.9, top_k=12, seed=seeds[i]))
    return eng.run()

  a = run(1, seeds=[7, 7, 9])
  b = run(3, seeds=[7, 7, 9])
  for i in range(len(prompts)):
    np.testing.assert_array_equal(a[i], b[i], err_msg=f"req {i}")
  # Same prompt + same seed -> same stream; different seed -> differs.
  np.testing.assert_array_equal(a[0][5:], a[1][5:])
  c = run(3, seeds=[7, 8, 9])
  assert not np.array_equal(a[1][5:], c[1][5:])


def test_sample_token_slots_matches_sample_logits_semantics():
  """The traced-parameter sampler mirrors sample_logits: greedy at
  temperature<=0 regardless of filters, top-k support restriction, and
  tiny top-p collapsing to argmax."""
  r = np.random.RandomState(0)
  logits = jnp.asarray(r.randn(16, 32), jnp.float32)
  keys = np.stack([np.asarray(jax.random.PRNGKey(i)) for i in range(16)])
  greedy = np.asarray(sample_logits(logits, jax.random.PRNGKey(0),
                                    temperature=0.0))
  zeros, ones = np.zeros(16, np.float32), np.ones(16, np.float32)

  out = sample_token_slots(logits, keys, jnp.zeros(16),
                           jnp.full(16, 5, jnp.int32), jnp.asarray(ones))
  np.testing.assert_array_equal(np.asarray(out), greedy)
  # tiny top_p keeps only the top token at any temperature.
  out = sample_token_slots(logits, keys, jnp.full(16, 1.5),
                           jnp.zeros(16, jnp.int32),
                           jnp.full(16, 1e-6, jnp.float32))
  np.testing.assert_array_equal(np.asarray(out), greedy)
  # top_k=1 collapses to greedy; k=0 leaves full support.
  out = sample_token_slots(logits, keys, jnp.full(16, 2.0),
                           jnp.ones(16, jnp.int32), jnp.asarray(ones))
  np.testing.assert_array_equal(np.asarray(out), greedy)
  k = 4
  topk_sets = np.asarray(jax.lax.top_k(logits, k)[1])
  out = np.asarray(sample_token_slots(
      logits, keys, jnp.full(16, 1.0), jnp.full(16, k, jnp.int32),
      jnp.asarray(ones)))
  assert all(out[i] in topk_sets[i] for i in range(16))
  # Per-slot parameters really are per-slot: slot 0 greedy, slot 1 hot.
  temps = jnp.asarray([0.0] + [5.0] * 15)
  out = np.asarray(sample_token_slots(logits, keys, temps,
                                      jnp.zeros(16, jnp.int32),
                                      jnp.asarray(ones)))
  assert out[0] == greedy[0]


# ---------------------------------------------------------------- scheduler


def test_scheduler_admission_budget_max_batch_fcfs():
  """Host-only: FCFS admission gated by free slots, max_batch and the
  per-step prefill-token budget; budget-starved prefills resume on later
  steps; decode tokens are never budget-gated."""
  sched = FCFSScheduler(num_slots=4, prefill_chunk=4, max_seq_len=64,
                        prefill_token_budget=8, max_batch=3)
  for i in range(4):
    sched.submit(Request(uid=i, prompt=np.arange(1, 7, dtype=np.int32),
                         max_new_tokens=3))
  plan = sched.plan_step()
  # Budget 8 = two first-chunks of 4: requests 0 and 1 admitted;
  # max_batch=3 would allow a third but the budget does not.
  assert plan.active_slots == 2
  assert plan.prefill_tokens == 8 and plan.decode_tokens == 0
  assert list(plan.num_valid[:2]) == [4, 4] and plan.reset[:2].all()
  sched.commit(np.zeros(4, np.int32))
  plan = sched.plan_step()
  # Remaining 2-token prefills (0,1) cost 4; budget admits request 2
  # (first chunk 4); max_batch=3 blocks request 3.
  assert plan.active_slots == 3
  assert plan.prefill_tokens == 8
  sched.commit(np.zeros(4, np.int32))
  plan = sched.plan_step()
  # 0 and 1 finished prefill last step -> decoding now (not budgeted).
  assert plan.decode_tokens == 2
  assert sched.pending and sched.pending[0].uid == 3  # still FCFS-queued


def test_scheduler_requires_plan_before_commit_and_validates():
  sched = FCFSScheduler(num_slots=1, prefill_chunk=2, max_seq_len=8)
  with pytest.raises(RuntimeError):
    sched.commit(np.zeros(1, np.int32))
  with pytest.raises(ValueError, match="non-empty"):
    sched.submit(Request(uid=0, prompt=np.zeros(0, np.int32),
                         max_new_tokens=1))
  with pytest.raises(ValueError, match="max_seq_len"):
    sched.submit(Request(uid=0, prompt=np.zeros(6, np.int32),
                         max_new_tokens=4))
  with pytest.raises(ValueError, match="top_p"):
    sched.submit(Request(uid=0, prompt=np.zeros(2, np.int32),
                         max_new_tokens=1, top_p=0.0))
  assert sched.plan_step() is None  # idle


def test_slot_allocator_free_list():
  alloc = SlotAllocator(3)
  assert [alloc.alloc() for _ in range(3)] == [0, 1, 2]
  assert alloc.alloc() is None
  alloc.free(1)
  assert alloc.num_free == 1 and alloc.alloc() == 1
  with pytest.raises(ValueError, match="double free"):
    alloc.free(2), alloc.free(2)


def test_kv_cache_shapes_and_config_validation():
  kv, cursors = allocate_kv_cache(TINY, num_slots=3, chunk=4)
  Lc = cache_length(TINY, 4)
  assert Lc == TINY.max_seq_len + 4
  assert set(kv) == {f"block_{i}" for i in range(TINY.num_layers)}
  leaf = kv["block_0"]["attn"]["cached_key"]
  assert leaf.shape == (3, Lc, TINY.num_heads,
                        TINY.d_model // TINY.num_heads)
  assert cursors.shape == (3,) and cursors.dtype == jnp.int32
  with pytest.raises(ValueError, match="prefill_token_budget"):
    epl.Config({"serving.prefill_token_budget": 2,
                "serving.prefill_chunk": 4})
  with pytest.raises(ValueError, match="num_slots"):
    epl.Config({"serving.num_slots": 0})


# ------------------------------------------------------------------ metrics


def test_serving_stats_rollup():
  t = [0.0]
  clock = lambda: t[0]
  stats = ServingStats(clock=clock)
  stats.note_submitted("a")
  t[0] = 1.0
  stats.note_admitted("a")
  t[0] = 2.0
  stats.note_first_token("a")
  t[0] = 5.0
  stats.note_finished("a", new_tokens=4)
  stats.note_step(active_slots=2, num_slots=4, prefill_tokens=8,
                  decode_tokens=2, step_time_s=0.5)
  stats.note_step(active_slots=4, num_slots=4, prefill_tokens=0,
                  decode_tokens=4, step_time_s=0.5)
  s = stats.summary()
  assert s["finished_requests"] == 1 and s["generated_tokens"] == 4
  assert s["ttft_p50_s"] == pytest.approx(2.0)   # submit 0 -> first at 2
  assert s["itl_mean_s"] == pytest.approx(1.0)   # (5-2)/(4-1)
  assert s["slot_occupancy_mean"] == pytest.approx(0.75)
  assert s["tokens_per_s"] == pytest.approx(4.0)
  assert percentile([1.0, 2.0, 3.0], 50) == 2.0
  assert percentile([], 99) == 0.0


def test_serving_stats_finished_limit_windows_traces():
  """serving.finished_limit: finished per-request traces evict
  oldest-first (latency percentiles become a sliding window) while
  aggregate counters keep the full history and in-flight traces are
  never evicted."""
  t = [0.0]
  stats = ServingStats(clock=lambda: t[0], finished_limit=2)
  for i, uid in enumerate(["a", "b", "c"]):
    t[0] = float(i)
    stats.note_submitted(uid)
    stats.note_first_token(uid)
    t[0] = float(i) + 0.5
    stats.note_finished(uid, new_tokens=1)
  stats.note_submitted("inflight")
  assert stats.finished_requests == 3          # aggregates: full history
  assert set(stats._req) == {"b", "c", "inflight"}  # traces: windowed
  cfg = __import__("easyparallellibrary_tpu").Config
  with pytest.raises(ValueError, match="finished_limit"):
    cfg({"serving": {"finished_limit": -1}})


# ------------------------------------------------------- pipeline fallback


def test_pp_generate_fallback_logged_once(caplog):
  """Satellite: generate() on a pipelined config logs the full-forward
  fallback exactly once per process (same latch pattern as the smap
  advisory), saying why."""
  from easyparallellibrary_tpu.models import gpt as gpt_mod
  epl.init()
  cfg = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                  d_ff=64, max_seq_len=16, dtype=jnp.float32,
                  pipeline_stages=2, pipeline_debug_sequential=True)
  model = GPT(cfg)
  prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
  params = model.init(jax.random.PRNGKey(0), prompt)["params"]
  from easyparallellibrary_tpu.utils.logging import get_logger
  logger = get_logger()
  old_propagate = logger.propagate
  gpt_mod._PP_GENERATE_FALLBACK_LOGGED[0] = False
  try:
    logger.propagate = True  # the repo logger is handler-only by default
    with caplog.at_level(logging.WARNING, logger=logger.name):
      generate(model, params, prompt, 2)
      generate(model, params, prompt, 2)
    hits = [r for r in caplog.records
            if "full-forward-per-token" in r.getMessage()]
    assert len(hits) == 1
    assert "pipeline_stages" in hits[0].getMessage()
  finally:
    logger.propagate = old_propagate
    gpt_mod._PP_GENERATE_FALLBACK_LOGGED[0] = False


# ------------------------------------------------------------ restore_params


def test_restore_params_from_trainstate_checkpoint(tmp_path):
  """Satellite: params-only restore from a FULL TrainState checkpoint —
  no optimizer/sentinel leaves touched — with the PR-2 fallback chain
  (corrupt newest checkpoint is quarantined and the previous restores)."""
  from easyparallellibrary_tpu.parallel import TrainState
  from easyparallellibrary_tpu.runtime.saver import (
      restore_params, save_checkpoint)
  from easyparallellibrary_tpu.testing.chaos import corrupt_shard
  epl.init()
  model, params = _model_and_params(seed=8)
  state = TrainState.create(apply_fn=model.apply, params=params,
                            tx=optax.adam(1e-3))
  root = str(tmp_path / "ckpt")
  save_checkpoint(root, state, step=3)
  p2 = jax.tree_util.tree_map(lambda x: x + 1.0, params)
  state2 = state.replace(params=p2)
  newest = save_checkpoint(root, state2, step=5)

  restored, step = restore_params(root, target=params)
  assert step == 5
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                 np.asarray(b)),
      restored, p2)
  # Raw-dict mode returns ONLY params leaves, prefix stripped.
  raw, _ = restore_params(root)
  assert all(not k.startswith(("opt_state", "step")) for k in raw)
  assert any(k.startswith("wte") for k in raw)

  # Newest checkpoint rots -> fallback chain lands on step 3.
  corrupt_shard(newest, shard=0, mode="flip")
  restored3, step3 = restore_params(root, target=params)
  assert step3 == 3
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                 np.asarray(b)),
      restored3, params)
  # The restored params drive the serving engine directly.
  (prompt,) = _prompts((4,), seed=9)
  eng = ContinuousBatchingEngine(model, restored3, num_slots=1,
                                 prefill_chunk=4)
  eng.submit(Request(uid=0, prompt=prompt, max_new_tokens=3))
  out = eng.run()
  np.testing.assert_array_equal(out[0], _oracle(model, params, prompt, 3))


def test_engine_rejects_pipelined_and_moe_configs():
  epl.init()
  model_pp = GPT(GPTConfig(**{**TINY.__dict__, "pipeline_stages": 2}))
  with pytest.raises(ValueError, match="pipeline"):
    ContinuousBatchingEngine(model_pp, {}, num_slots=1)
  model_moe = GPT(GPTConfig(**{**TINY.__dict__, "num_experts": 2}))
  with pytest.raises(ValueError, match="MoE"):
    ContinuousBatchingEngine(model_moe, {}, num_slots=1)
