"""Pipeline parallelism tests (reference analog: tests/scheduler_test.py +
the pipeline numeric-equivalence style)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.parallel.pipeline import Pipeline, bubble_fraction
from easyparallellibrary_tpu.parallel.partitioner import (
    find_repeated_blocks, partition_balance, partition_stages)
from easyparallellibrary_tpu.strategies.scheduler import get_scheduler


from easyparallellibrary_tpu import ops


class ToyStage(nn.Module):
  """One stage: Dense + nonlinearity (shape-preserving)."""
  width: int = 16

  @nn.compact
  def __call__(self, x):
    return jnp.tanh(ops.Dense(self.width, parallel="none")(x))


def _pipelines(S=4, M=4, sequential=False):
  return Pipeline(stage_module_cls=ToyStage,
                  stage_kwargs=dict(width=16),
                  num_stages=S, num_micro_batch=M,
                  sequential=sequential)


@pytest.mark.quick
def test_pipeline_matches_sequential():
  epl.init()
  mesh = epl.init().cluster.build_mesh(stage=4)
  x = jnp.asarray(np.random.RandomState(0).randn(16, 16), jnp.float32)

  pipe = _pipelines(sequential=False)
  seq = _pipelines(sequential=True)
  params = pipe.init(jax.random.PRNGKey(0), x)["params"]

  out_pipe = jax.jit(lambda p, v: pipe.apply({"params": p}, v))(params, x)
  out_seq = jax.jit(lambda p, v: seq.apply({"params": p}, v))(params, x)
  np.testing.assert_allclose(out_pipe, out_seq, rtol=1e-5, atol=1e-6)


def test_pipeline_grads_match_sequential():
  epl.init()
  mesh = epl.init().cluster.build_mesh(stage=4)
  x = jnp.asarray(np.random.RandomState(1).randn(16, 16), jnp.float32)

  pipe = _pipelines(sequential=False)
  seq = _pipelines(sequential=True)
  params = pipe.init(jax.random.PRNGKey(0), x)["params"]

  def loss(apply_mod):
    return lambda p: jnp.mean(apply_mod.apply({"params": p}, x) ** 2)

  g_pipe = jax.jit(jax.grad(loss(pipe)))(params)
  g_seq = jax.jit(jax.grad(loss(seq)))(params)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
      g_pipe, g_seq)


def test_stage_params_sharded_on_stage_axis():
  env = epl.init()
  mesh = env.cluster.build_mesh(stage=4)
  x = jnp.ones((16, 16))
  pipe = _pipelines()

  from easyparallellibrary_tpu.parallel import (
      create_sharded_train_state, TrainState)

  def init_fn(rng):
    return TrainState.create(apply_fn=pipe.apply,
                             params=pipe.init(rng, x)["params"],
                             tx=optax.sgd(0.1))

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  kernel = state.params["stages"]["stacked"]["Dense_0"]["kernel"].value
  assert kernel.shape[0] == 4  # stacked stage dim
  assert kernel.sharding.shard_shape(kernel.shape)[0] == 1  # 1 stage/group


def test_pipeline_training_decreases_loss():
  env = epl.init()
  mesh = env.cluster.build_mesh(stage=4)
  x = jnp.asarray(np.random.RandomState(0).randn(16, 16), jnp.float32)
  y = jnp.asarray(np.random.RandomState(1).randn(16, 16), jnp.float32)

  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state, make_train_step, parallelize)

  pipe = _pipelines()

  def init_fn(rng):
    return TrainState.create(apply_fn=pipe.apply,
                             params=pipe.init(rng, x)["params"],
                             tx=optax.adam(1e-2))

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))

  def loss_fn(params, batch, rng):
    pred = pipe.apply({"params": params}, batch["x"])
    return jnp.mean((pred - batch["y"]) ** 2), {}

  step = parallelize(make_train_step(loss_fn), mesh, shardings)
  losses = []
  for _ in range(10):
    state, m = step(state, {"x": x, "y": y}, jax.random.PRNGKey(1))
    losses.append(float(m["loss"]))
  assert losses[-1] < losses[0]


@pytest.mark.slow
def test_gpt_pipeline_matches_gpt_sequential():
  from easyparallellibrary_tpu.models import GPT, GPTConfig
  from easyparallellibrary_tpu.models.gpt import gpt_loss

  env = epl.init()
  mesh = env.cluster.build_mesh(stage=2)
  base = dict(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
              d_ff=64, max_seq_len=16, dtype=jnp.float32,
              pipeline_stages=2, num_micro_batch=4)
  pp = GPT(GPTConfig(**base))
  seq = GPT(GPTConfig(**base, pipeline_debug_sequential=True))

  # micro-batch size (B/M) must divide the data axis (4 here).
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (16, 17)),
                    jnp.int32)
  params = pp.init(jax.random.PRNGKey(0), ids[:, :-1])["params"]

  l_pp, _ = jax.jit(lambda p: gpt_loss(pp, p, {"ids": ids}))(params)
  l_seq, _ = jax.jit(lambda p: gpt_loss(seq, p, {"ids": ids}))(params)
  np.testing.assert_allclose(float(l_pp), float(l_seq), rtol=1e-5)

  g_pp = jax.jit(jax.grad(lambda p: gpt_loss(pp, p, {"ids": ids})[0]))(params)
  g_seq = jax.jit(jax.grad(lambda p: gpt_loss(seq, p, {"ids": ids})[0]))(
      params)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-3, atol=1e-5),
      g_pp, g_seq)


def test_pipeline_batch_not_divisible_raises():
  epl.init().cluster.build_mesh(stage=4)
  pipe = _pipelines(S=4, M=3)
  with pytest.raises(ValueError):
    pipe.init(jax.random.PRNGKey(0), jnp.ones((16, 16)))


def test_bubble_fraction():
  assert bubble_fraction(4, 4) == pytest.approx(3 / 7)
  assert bubble_fraction(1, 8) == 0.0


def test_scheduler_registry():
  assert get_scheduler("PreferForward").remat_stage is False
  assert get_scheduler("PreferBackward").remat_stage is True
  assert get_scheduler("PreferBackwardOptimizer").grouped_apply is True
  with pytest.raises(ValueError):
    get_scheduler("bogus")


def test_partition_balance():
  ranges = partition_balance([1, 1, 1, 1, 8, 1, 1, 1], 2)
  assert len(ranges) == 2
  # The heavy item should not share a part with everything else.
  sums = [sum([1, 1, 1, 1, 8, 1, 1, 1][s:e]) for s, e in ranges]
  assert max(sums) <= 12 - min(sums) or max(sums) == 8 + 3


def test_partition_stages_and_repeated_blocks():
  names = [f"block_{i}" for i in range(8)] + ["ln_f"]
  groups = find_repeated_blocks(names)
  assert groups["block_#"] == [f"block_{i}" for i in range(8)]
  stages = partition_stages([f"block_{i}" for i in range(8)], 4)
  assert [len(s) for s in stages] == [2, 2, 2, 2]
  assert stages[0] == ["block_0", "block_1"]


def test_auto_stage_generator_policies():
  from easyparallellibrary_tpu.parallel.planner import AutoStageGenerator

  epl.init(epl.Config({"auto.auto_parallel": True,
                       "pipeline.num_stages": 2}))
  names = ["embed"] + [f"block_{i}" for i in range(6)] + ["head"]
  params = {n: 100 for n in names}
  params["embed"] = 500
  params["head"] = 500

  gen = AutoStageGenerator(policy="balance_param")
  stages = gen.search(names, block_params=params)
  assert len(stages) == 2
  assert sum(len(s) for s in stages) == len(names)
  w = [sum(params[n] for n in s) for s in stages]
  assert max(w) <= 900  # balanced: each side keeps one heavy end

  gen2 = AutoStageGenerator(policy="repeated_layers", num_stages=2)
  stages2 = gen2.search(names, block_params=params)
  assert stages2[0][0] == "embed" and stages2[-1][-1] == "head"
  assert len(stages2) == 2


def test_repeated_layers_policy_covers_all_blocks():
  from easyparallellibrary_tpu.parallel.planner import AutoStageGenerator
  epl.init()
  names = ["emb", "attn_0", "mlp_0", "attn_1", "mlp_1", "head"]
  gen = AutoStageGenerator(policy="repeated_layers", num_stages=2)
  stages = gen.search(names)
  flat = [n for s in stages for n in s]
  assert flat == names  # contiguous, nothing dropped
  assert len(stages) == 2


def test_gpt_interleaved_pipeline_matches_sequential():
  from easyparallellibrary_tpu.models import GPT, GPTConfig
  from easyparallellibrary_tpu.models.gpt import gpt_loss

  env = epl.init()
  mesh = env.cluster.build_mesh(stage=2)
  base = dict(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
              d_ff=64, max_seq_len=16, dtype=jnp.float32,
              pipeline_stages=2, num_micro_batch=2, pipeline_interleave=2)
  pp = GPT(GPTConfig(**base))
  seq = GPT(GPTConfig(**base, pipeline_debug_sequential=True))
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 17)),
                    jnp.int32)
  params = pp.init(jax.random.PRNGKey(0), ids[:, :-1])["params"]
  assert "pipeline_0" in params and "pipeline_1" in params
  l_pp, _ = jax.jit(lambda p: gpt_loss(pp, p, {"ids": ids}))(params)
  l_seq, _ = jax.jit(lambda p: gpt_loss(seq, p, {"ids": ids}))(params)
  np.testing.assert_allclose(float(l_pp), float(l_seq), rtol=1e-5)


def test_scan_mode_matches_unrolled():
  epl.init()
  mesh = epl.init().cluster.build_mesh(stage=4)
  x = jnp.asarray(np.random.RandomState(2).randn(32, 16), jnp.float32)
  unrolled = Pipeline(stage_module_cls=ToyStage, stage_kwargs=dict(width=16),
                      num_stages=4, num_micro_batch=8, use_scan=False)
  scanned = Pipeline(stage_module_cls=ToyStage, stage_kwargs=dict(width=16),
                     num_stages=4, num_micro_batch=8, use_scan=True)
  params = unrolled.init(jax.random.PRNGKey(0), x)["params"]
  o1 = jax.jit(lambda p: unrolled.apply({"params": p}, x))(params)
  o2 = jax.jit(lambda p: scanned.apply({"params": p}, x))(params)
  np.testing.assert_allclose(o1, o2, rtol=1e-5, atol=1e-6)

  g1 = jax.jit(jax.grad(
      lambda p: jnp.mean(unrolled.apply({"params": p}, x) ** 2)))(params)
  g2 = jax.jit(jax.grad(
      lambda p: jnp.mean(scanned.apply({"params": p}, x) ** 2)))(params)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
      g1, g2)


def test_auto_stage_from_cost_model():
  from easyparallellibrary_tpu.parallel.planner import AutoStageGenerator
  epl.init(epl.Config({"pipeline.num_stages": 2}))
  x = jnp.ones((4, 64))
  w_small = jnp.ones((64, 64))
  w_big = jnp.ones((64, 512))
  fns = {
      "small_0": lambda v: v @ w_small,
      "small_1": lambda v: v @ w_small,
      "big": lambda v: (v @ w_big) @ w_big.T,
      "small_2": lambda v: v @ w_small,
  }
  gen = AutoStageGenerator(num_stages=2)
  stages = gen.search_from_cost_model(fns, x)
  flat = [n for s in stages for n in s]
  assert flat == list(fns)
  # The expensive block should sit alone-ish: both stages non-empty and
  # "big" not grouped with all three smalls.
  big_stage = [s for s in stages if "big" in s][0]
  assert len(big_stage) < 4
