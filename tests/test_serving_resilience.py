"""Serving resilience: deadlines, admission control & shedding,
bad-step recovery, and the serving chaos harness (ISSUE 6).

The acceptance contract (`make chaos-serve`): under injected NaN steps,
hung steps, flaky drafters and Poisson overload, every NON-SHED request
finishes with greedy output bit-exact vs ``generate(use_cache=True)``,
shed/expired requests carry the right finish reasons, and the fused
step's compile count stays 1 across retries, degradation transitions
and slot requeues.  The heavyweight chaos episodes are ``slow``-marked
(tier-1 window budget — ROADMAP); ``make chaos-serve`` runs them all.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.models.gpt import generate
from easyparallellibrary_tpu.serving import (
    AdmissionController, BadStepPolicy, ContinuousBatchingEngine,
    FCFSScheduler, Request)
from easyparallellibrary_tpu.serving.speculative import NgramDrafter
from easyparallellibrary_tpu.testing import chaos

TINY = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                 d_ff=64, max_seq_len=32, dtype=jnp.float32)


def _model_and_params(cfg=TINY, seed=0):
  model = GPT(cfg)
  params = model.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, 4), jnp.int32))["params"]
  return model, params


def _prompts(lengths, vocab=64, seed=0):
  r = np.random.RandomState(seed)
  return [r.randint(0, vocab, (n,)).astype(np.int32) for n in lengths]


def _oracle(model, params, prompt, max_new):
  return np.asarray(
      generate(model, params, jnp.asarray(prompt)[None], max_new))[0]


def _res_config(**resilience):
  resilience.setdefault("enabled", True)
  return epl.Config({"serving": {"resilience": resilience}})


class FakeClock:
  """Injectable monotonic clock for deterministic deadline tests."""

  def __init__(self, t: float = 0.0):
    self.t = t

  def __call__(self) -> float:
    return self.t

  def advance(self, dt: float):
    self.t += dt


def _sched(clock, num_slots=2, chunk=4, **kw):
  return FCFSScheduler(num_slots=num_slots, prefill_chunk=chunk,
                       max_seq_len=32, clock=clock, **kw)


# ------------------------------------------------------------ policy units


def test_admission_ladder_escalates_in_cost_order():
  """Queue pressure walks the ladder normal -> spec_off -> budget_tight
  -> shed; level 2 additionally requires full slot occupancy (tightening
  the budget while slots sit empty would slow the draining admissions).
  """
  seen = []
  ctl = AdmissionController(
      queue_limit=8, degrade_queue_frac=0.5,
      on_transition=lambda old, new, sig: seen.append((old, new)))
  assert ctl.observe(1, 1.0) == 0
  assert ctl.observe(4, 1.0) == 1           # frac 0.5 -> spec_off
  assert ctl.speculation_enabled is False
  assert ctl.observe(6, 0.5) == 1           # frac 0.75 but slots free
  assert ctl.observe(6, 1.0) == 2           # full slots -> budget_tight
  assert ctl.budget_tightened is True
  assert ctl.observe(8, 1.0) == 3           # full queue -> shed
  assert ctl.should_shed(8) is True
  assert seen == [(0, 1), (1, 2), (2, 3)]
  assert ctl.transitions == 3


def test_admission_ladder_deescalates_with_hysteresis():
  """De-escalation is one level per observation and only once the queue
  has drained below HALF the level's entry threshold — a noisy boundary
  cannot flap the ladder."""
  ctl = AdmissionController(queue_limit=8, degrade_queue_frac=0.5)
  ctl.observe(8, 1.0)
  assert ctl.level == 3
  assert ctl.observe(5, 1.0) == 3   # frac 0.625 >= 0.5 * enter(3): hold
  assert ctl.observe(3, 1.0) == 2   # clear of 3, one level down
  assert ctl.observe(0, 1.0) == 1   # one level per call, even at empty
  assert ctl.observe(0, 1.0) == 0
  # 0->3 escalation is ONE immediate transition; the descent is three.
  assert ctl.transitions == 4


def test_admission_itl_slo_forces_spec_off():
  """A measured ITL above its SLO forces at least spec_off regardless of
  queue depth (draft compute is the first ballast), and holds the level
  until the ITL recovers."""
  ctl = AdmissionController(queue_limit=8, itl_slo_s=0.01)
  assert ctl.observe(0, 0.5, itl_s=0.05) == 1
  assert ctl.speculation_enabled is False
  assert ctl.observe(0, 0.5, itl_s=0.05) == 1   # still over: hold
  assert ctl.observe(0, 0.5, itl_s=0.001) == 0
  # An unbounded queue (queue_limit=0) still honors the ITL signal.
  ctl = AdmissionController(queue_limit=0, itl_slo_s=0.01)
  assert ctl.observe(100, 1.0, itl_s=0.0) == 0  # depth alone: no signal
  assert ctl.observe(0, 0.0, itl_s=0.02) == 1


def test_admission_sheds_on_full_queue_before_ladder():
  ctl = AdmissionController(queue_limit=2)
  assert ctl.should_shed(1) is False
  # Pure predicate: polling it never inflates the shed counter; the
  # caller that acts on the verdict records the shed explicitly.
  assert ctl.should_shed(2) is True
  assert ctl.should_shed(2) is True
  assert ctl.shed_total == 0
  ctl.note_shed()
  assert ctl.shed_total == 1


def test_bad_step_policy_retry_then_requeue_then_fail():
  class S:  # the two fields judge() reads off scheduler slot state
    def __init__(self):
      self.bad_streak = 0
      self.requeues = 0

  pol = BadStepPolicy(max_step_retries=1, max_requeues=1)
  slots = {0: S(), 1: S()}
  assert pol.judge(slots, [0]) == {0: "retry"}        # streak 1: retry
  assert pol.judge(slots, [0]) == {0: "requeue"}      # streak 2: out
  slots[0].requeues = 1                                # scheduler did it
  slots[0].bad_streak = 0
  assert pol.judge(slots, [0]) == {0: "retry"}        # fresh slot life
  assert pol.judge(slots, [0]) == {0: "fail"}         # requeues spent
  assert pol.judge(slots, []) == {}                   # good step resets
  assert slots[1].bad_streak == 0
  assert pol.counters() == {"bad_steps": 4, "step_retries": 2,
                            "requeues": 1, "failed_requests": 1}


def test_request_lifecycle_field_validation():
  clock = FakeClock()
  sched = _sched(clock)
  (p,) = _prompts((3,))
  with pytest.raises(ValueError, match="priority"):
    sched.submit(Request(uid=0, prompt=p, max_new_tokens=2,
                         priority="realtime"))
  with pytest.raises(ValueError, match="deadline_s"):
    sched.submit(Request(uid=0, prompt=p, max_new_tokens=2,
                         deadline_s=-1.0))
  with pytest.raises(ValueError, match="ttft_budget_s"):
    sched.submit(Request(uid=0, prompt=p, max_new_tokens=2,
                         deadline_s=1.0, ttft_budget_s=2.0))


def test_resilience_config_validation():
  with pytest.raises(ValueError, match="queue_limit"):
    _res_config(queue_limit=-1)
  with pytest.raises(ValueError, match="degrade_queue_frac"):
    _res_config(degrade_queue_frac=1.5)
  with pytest.raises(ValueError, match="step_timeout_s"):
    _res_config(step_timeout_s=-0.1)
  with pytest.raises(ValueError, match="max_step_retries"):
    _res_config(max_step_retries=-1)


# ------------------------------------------- scheduler lifecycle control


def test_deadline_expires_queued_request():
  clock = FakeClock()
  sched = _sched(clock, num_slots=1)
  a, b = _prompts((3, 3))
  sched.submit(Request(uid="a", prompt=a, max_new_tokens=4))
  sched.submit(Request(uid="b", prompt=b, max_new_tokens=4,
                       deadline_s=5.0))
  sched.plan_step()          # slot goes to "a"; "b" waits in queue
  clock.advance(6.0)
  sched.plan_step()
  fins = {f.uid: f for f in sched.take_finished()}
  assert fins["b"].finish_reason == "deadline"
  assert fins["b"].new_tokens == 0
  np.testing.assert_array_equal(fins["b"].tokens, b)   # prompt returned
  assert "a" not in fins                               # no deadline set


def test_deadline_expires_active_request_with_partial_output():
  clock = FakeClock()
  sched = _sched(clock, num_slots=1)
  (p,) = _prompts((3,))
  sched.submit(Request(uid="a", prompt=p, max_new_tokens=8,
                       deadline_s=10.0))
  sched.plan_step()
  sched.commit(np.asarray([7, 0], np.int32))   # prefill done: 1 token
  clock.advance(11.0)
  assert sched.plan_step() is None
  (fin,) = sched.take_finished()
  assert fin.finish_reason == "deadline" and fin.new_tokens == 1
  np.testing.assert_array_equal(fin.tokens, list(p) + [7])


def test_ttft_budget_only_binds_before_first_token():
  clock = FakeClock()
  sched = _sched(clock, num_slots=2)
  a, b = _prompts((3, 3))
  # "slow" never gets scheduled tokens before its TTFT budget passes.
  sched.submit(Request(uid="slow", prompt=a, max_new_tokens=8,
                       ttft_budget_s=1.0))
  sched.submit(Request(uid="fast", prompt=b, max_new_tokens=8,
                       ttft_budget_s=5.0))
  sched.plan_step()
  sched.commit(np.asarray([3, 3], np.int32))   # both emit first token
  clock.advance(2.0)                           # past "slow"'s budget...
  sched.plan_step()
  assert not sched.take_finished()             # ...but token was in time
  clock.advance(10.0)                          # past both budgets: moot
  sched.plan_step()
  assert not sched.take_finished()


def test_ttft_budget_expires_unserved_request():
  clock = FakeClock()
  sched = _sched(clock, num_slots=1)
  a, b = _prompts((3, 3))
  sched.submit(Request(uid="a", prompt=a, max_new_tokens=8))
  sched.submit(Request(uid="b", prompt=b, max_new_tokens=8,
                       ttft_budget_s=1.0))     # stuck behind "a"
  sched.plan_step()
  clock.advance(1.5)
  sched.plan_step()
  (fin,) = sched.take_finished()
  assert fin.uid == "b" and fin.finish_reason == "deadline"


def test_cancel_queued_and_active():
  clock = FakeClock()
  sched = _sched(clock, num_slots=1)
  a, b = _prompts((3, 3))
  sched.submit(Request(uid="a", prompt=a, max_new_tokens=8))
  sched.submit(Request(uid="b", prompt=b, max_new_tokens=8))
  sched.plan_step()
  sched.commit(np.asarray([5, 0], np.int32))
  assert sched.cancel("b") is True             # still queued
  assert sched.cancel("a") is True             # active, 1 token in
  assert sched.cancel("ghost") is False        # unknown uid
  fins = {f.uid: f for f in sched.take_finished()}
  assert fins["b"].finish_reason == "cancelled"
  assert fins["b"].new_tokens == 0
  assert fins["a"].finish_reason == "cancelled"
  assert fins["a"].new_tokens == 1
  assert not sched.has_work


def test_latency_class_jumps_fcfs_order():
  clock = FakeClock()
  sched = _sched(clock, num_slots=1)
  admitted = []
  sched.on_admit.append(admitted.append)
  a, b, c = _prompts((3, 3, 3))
  sched.submit(Request(uid="t1", prompt=a, max_new_tokens=1))
  sched.plan_step()                            # t1 takes the only slot
  sched.submit(Request(uid="t2", prompt=b, max_new_tokens=1))
  sched.submit(Request(uid="lat", prompt=c, max_new_tokens=1,
                       priority="latency"))
  sched.commit(np.asarray([1], np.int32))      # t1 finishes (length)
  sched.plan_step()                            # freed slot: lat jumps t2
  sched.commit(np.asarray([1], np.int32))
  sched.plan_step()
  sched.commit(np.asarray([1], np.int32))
  assert admitted == ["t1", "lat", "t2"]


def test_on_finish_subscribers_compose():
  """The hooks are subscriber LISTS — engine stats and resilience
  callbacks must not clobber each other (ISSUE 6 satellite: the old
  single-callback slot was silently overwritten)."""
  clock = FakeClock()
  sched = _sched(clock, num_slots=1)
  got_a, got_b = [], []
  sched.on_finish.append(lambda fin: got_a.append(fin.uid))
  sched.on_finish.append(lambda fin: got_b.append(fin.uid))
  (p,) = _prompts((3,))
  sched.submit(Request(uid="x", prompt=p, max_new_tokens=1))
  sched.plan_step()
  sched.commit(np.asarray([1], np.int32))
  assert got_a == ["x"] and got_b == ["x"]


def test_requeue_slot_carries_committed_prefix():
  clock = FakeClock()
  sched = _sched(clock, num_slots=1)
  (p,) = _prompts((3,))
  sched.submit(Request(uid="r", prompt=p, max_new_tokens=8))
  sched.plan_step()
  sched.commit(np.asarray([9], np.int32))      # prefill done + 1 token
  assert sched.requeue_slot(0) == "r"
  assert sched.queue_depth == 1 and not sched.active
  entry = sched.pending[0]
  assert entry.prefix_len == len(p) + 1
  plan = sched.plan_step()                     # readmitted: replay
  assert plan.reset[0] and plan.prefilling[0]
  np.testing.assert_array_equal(plan.tokens[0, :4], list(p) + [9])
  # The replayed last-prefix sample IS the next stream token — it
  # commits (same tok_index fold as the undisturbed decode step).
  assert plan.tok_index[0] == 1
  sched.commit(np.asarray([4], np.int32))
  assert sched.active[0].generated == [9, 4]


# ------------------------------------------------------- engine, no faults


@pytest.mark.quick
def test_fault_free_resilient_engine_bit_exact_zero_recompile():
  """Quick acceptance: resilience enabled but no faults injected is a
  pure no-op — token streams bit-identical to the baseline engine (and
  the generate() oracle), with the fused step still compiled ONCE (the
  finiteness verdict rides the same program)."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3, 9, 2))
  max_new = (6, 7, 4, 5)

  def drive(resilient):
    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   prefill_chunk=4, resilience=resilient)
    for i in range(2):
      eng.submit(Request(uid=i, prompt=prompts[i],
                         max_new_tokens=max_new[i]))
    out = {}
    for _ in range(2):
      for fin in eng.step():
        out[fin.uid] = fin.tokens
    for i in range(2, 4):                      # staggered second wave
      eng.submit(Request(uid=i, prompt=prompts[i],
                         max_new_tokens=max_new[i]))
    out.update(eng.run())
    assert eng._step_fn._cache_size() == 1
    return out

  base, res = drive(False), drive(True)
  assert sorted(base) == sorted(res) == list(range(4))
  for i in range(4):
    np.testing.assert_array_equal(res[i], base[i], err_msg=f"req {i}")
    np.testing.assert_array_equal(
        res[i], _oracle(model, params, prompts[i], max_new[i]))


def test_engine_compile_once_after_ambient_mesh_built():
  """Regression for the fit->engine recompile interplay (ROADMAP item 1
  'First'; NOTES.md): once any component builds the cluster mesh (fit's
  setup does), the fused step's activation constraints bind to it, so a
  meshless engine's first-call input shardings used to disagree with
  its output shardings — one recompile on call 2.  The engine now
  adopts the ambient mesh at construction; the step must stay at ONE
  compile in this construction order, and outputs stay exact."""
  epl.init(epl.Config({"cluster.mesh_shape": "data:4,model:2"}))
  epl.Env.get().cluster.build_mesh()           # what fit() does first
  model, params = _model_and_params()
  prompts = _prompts((5, 3), seed=4)
  eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                 prefill_chunk=4)   # mesh NOT passed
  for i, p in enumerate(prompts):
    eng.submit(Request(uid=i, prompt=p, max_new_tokens=6))
  out = eng.run()
  assert eng._step_fn._cache_size() == 1, \
      "fused step recompiled after build_mesh() — ambient-mesh adoption " \
      "regressed (NOTES.md: fit->engine recompile interplay)"
  for i, p in enumerate(prompts):
    np.testing.assert_array_equal(out[i], _oracle(model, params, p, 6))


# --------------------------------------------------------- chaos: NaN step


def test_nan_step_retried_in_place_bit_exact():
  """A transient NaN device step is retried exactly: the bad step never
  advanced cursors, the replan re-feeds identical work, and the final
  stream is bit-identical to the oracle — with the one compiled step
  reused across the retry."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3))
  eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                 prefill_chunk=4, resilience=True)
  inj = chaos.NaNLogitsInjector(eng, bad_calls=(2,))
  for i, p in enumerate(prompts):
    eng.submit(Request(uid=i, prompt=p, max_new_tokens=6))
  out = eng.run()
  assert inj.poisoned == [2]
  assert inj._cache_size() == 1
  assert eng.stats.bad_steps == 1 and eng.stats.step_retries >= 1
  for i, p in enumerate(prompts):
    assert eng.finished[i].finish_reason == "length"
    np.testing.assert_array_equal(out[i], _oracle(model, params, p, 6),
                                  err_msg=f"req {i}")


@pytest.mark.slow
def test_persistent_nan_quarantines_and_replays_prefix_bit_exact():
  """Two consecutive bad steps exceed max_step_retries=1: the slot is
  quarantined — its request requeued with the committed prefix intact —
  and the chunked-prefill replay reconstructs KV/cursor state exactly,
  so the final output is STILL bit-identical to the oracle."""
  epl.init()
  model, params = _model_and_params()
  (p,) = _prompts((5,))
  eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                 prefill_chunk=4, resilience=True)
  # Call 0-1: prefill (5 tokens over chunk 4).  Calls 2 and 3: the first
  # decode step and its in-place retry, both poisoned -> quarantine.
  inj = chaos.NaNLogitsInjector(eng, bad_calls=(2, 3))
  eng.submit(Request(uid="q", prompt=p, max_new_tokens=6))
  out = eng.run()
  assert inj.poisoned == [2, 3]
  assert inj._cache_size() == 1
  assert eng.stats.requeues == 1
  assert eng.finished["q"].finish_reason == "length"
  np.testing.assert_array_equal(out["q"], _oracle(model, params, p, 6))


@pytest.mark.slow
def test_requeue_overflow_fails_request_not_batch():
  """A request implicated past max_requeues is FAILED — it must not
  poison the batch forever; a healthy request sharing the engine still
  finishes bit-exactly."""
  epl.init()
  model, params = _model_and_params()
  bad_p, good_p = _prompts((5, 3), seed=7)
  eng = ContinuousBatchingEngine(
      model, params, num_slots=1, prefill_chunk=4,
      config=_res_config(max_step_retries=0, max_requeues=0))
  inj = chaos.NaNLogitsInjector(eng, bad_calls=(1,))
  eng.submit(Request(uid="bad", prompt=bad_p, max_new_tokens=6))
  eng.submit(Request(uid="good", prompt=good_p, max_new_tokens=6))
  out = eng.run()
  # Call 1 finished "bad"'s prefill: its verdict was poisoned, and with
  # zero retries/requeues budgeted the request fails with its committed
  # prefix returned; the slot then serves "good" untouched.
  assert eng.finished["bad"].finish_reason == "failed"
  assert inj._cache_size() == 1
  assert eng.finished["good"].finish_reason == "length"
  np.testing.assert_array_equal(out["good"],
                                _oracle(model, params, good_p, 6))


# ------------------------------------------------- chaos: hangs & drafters


@pytest.mark.slow
def test_hung_step_trips_watchdog_outputs_exact():
  """A stalled device call surfaces through the serving watchdog (log +
  counter) without being interrupted — a hang is a latency fault, and
  the stream stays bit-exact through it."""
  epl.init()
  model, params = _model_and_params()
  (p,) = _prompts((4,))
  eng = ContinuousBatchingEngine(
      model, params, num_slots=1, prefill_chunk=4,
      config=_res_config(step_timeout_s=0.05))
  try:
    inj = chaos.HangingStepInjector(eng, hang_calls=(1,), hang_s=0.4)
    eng.submit(Request(uid="h", prompt=p, max_new_tokens=5))
    out = eng.run()
  finally:
    eng.close()
  assert inj.hangs == 1
  assert eng.stats.watchdog_timeouts >= 1
  np.testing.assert_array_equal(out["h"], _oracle(model, params, p, 5))


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["raise", "garbage"])
def test_flaky_drafter_never_costs_correctness(mode):
  """A drafter that raises degrades to zero drafts for the step; one
  that proposes garbage has it rejected by verification — either way
  greedy output stays bit-exact and the step stays compiled once."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((5, 3), seed=2)
  eng = ContinuousBatchingEngine(
      model, params, num_slots=2, prefill_chunk=4, resilience=True,
      drafter=chaos.FlakyDrafter(NgramDrafter(k=2), bad_calls=(1, 3),
                                 mode=mode))
  for i, p in enumerate(prompts):
    eng.submit(Request(uid=i, prompt=p, max_new_tokens=8))
  out = eng.run()
  assert eng.drafter.faults >= 1
  assert eng._step_fn._cache_size() == 1
  if mode == "raise":
    assert eng._drafter_failures >= 1
  for i, p in enumerate(prompts):
    np.testing.assert_array_equal(out[i], _oracle(model, params, p, 8),
                                  err_msg=f"req {i} ({mode})")


# ----------------------------------------------------- overload & shedding


@pytest.mark.slow
def test_bounded_queue_sheds_at_submit():
  """Submits beyond queue_limit are rejected NOW (reason "shed", submit
  returns False) instead of waiting hopelessly; every accepted request
  still finishes bit-exactly."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((3, 4, 3, 5), seed=5)
  eng = ContinuousBatchingEngine(
      model, params, num_slots=1, prefill_chunk=4, max_batch=1,
      config=_res_config(queue_limit=2))
  accepted = [eng.submit(Request(uid=i, prompt=p, max_new_tokens=4))
              for i, p in enumerate(prompts)]
  assert accepted == [True, True, False, False]
  assert eng.finished[2].finish_reason == "shed"
  assert eng.finished[3].finish_reason == "shed"
  assert eng.stats.shed_requests == 2
  out = eng.run()
  assert sorted(out) == [0, 1]
  for i in (0, 1):
    np.testing.assert_array_equal(
        out[i], _oracle(model, params, prompts[i], 4), err_msg=f"req {i}")


@pytest.mark.slow
def test_stale_shed_level_clears_on_idle_submit():
  """Regression: the ladder de-escalates inside step(), but an idle
  engine never steps — if the queue drained without stepping (every
  request cancelled after a shed-level observation), a stale shed level
  must not reject 100% of traffic forever.  submit() re-observes the
  (idle) load signals first."""
  epl.init()
  model, params = _model_and_params()
  prompts = _prompts((3, 4, 3, 5), seed=7)
  eng = ContinuousBatchingEngine(
      model, params, num_slots=1, prefill_chunk=4, max_batch=1,
      config=_res_config(queue_limit=2, degrade_queue_frac=0.25))
  eng.submit(Request(uid=0, prompt=prompts[0], max_new_tokens=4))
  eng.step()                      # request 0 occupies the single slot
  eng.submit(Request(uid=1, prompt=prompts[1], max_new_tokens=4))
  eng.submit(Request(uid=3, prompt=prompts[3], max_new_tokens=4))
  eng.step()                      # no free slot: backlog 2/2 -> shed
  assert eng._admission.level == 3
  assert eng.cancel(1) and eng.cancel(3) and eng.cancel(0)
  assert not eng.has_work         # drained without another step
  assert eng.submit(Request(uid="fresh", prompt=prompts[2],
                            max_new_tokens=4)), \
      "idle engine with a stale shed level must accept new work"
  out = eng.run()
  np.testing.assert_array_equal(
      out["fresh"], _oracle(model, params, prompts[2], 4))
  assert eng._step_fn._cache_size() == 1


@pytest.mark.slow
def test_poisson_overload_episode_chaos_acceptance():
  """The `make chaos-serve` overload headline: a Poisson arrival burst
  against a bounded queue walks the degradation ladder (speculation off
  -> budget tightened -> shed) and back down; every NON-shed request
  finishes bit-exact vs generate(use_cache=True), every shed one
  carries reason "shed", and the fused step compiles exactly once
  across all transitions."""
  epl.init()
  model, params = _model_and_params()
  n = 12
  prompts = _prompts(tuple(3 + (i % 4) for i in range(n)), seed=6)
  arrivals = chaos.poisson_trace(rate_per_s=400.0, n=n, seed=1)
  eng = ContinuousBatchingEngine(
      model, params, num_slots=2, prefill_chunk=4,
      drafter=NgramDrafter(k=2),
      config=_res_config(queue_limit=4, degrade_queue_frac=0.25))
  # Drive arrivals against engine steps: each step advances "time" by
  # one mean service tick, submitting whatever arrived since.
  t, tick, nxt = 0.0, 1.0 / 400.0, 0
  while nxt < n or eng.has_work:
    t += tick
    while nxt < n and arrivals[nxt] <= t:
      eng.submit(Request(uid=nxt, prompt=prompts[nxt],
                         max_new_tokens=4))
      nxt += 1
    eng.step()
  assert eng._step_fn._cache_size() == 1
  shed = {u for u, f in eng.finished.items() if f.finish_reason == "shed"}
  assert shed, "overload episode never shed — not an overload"
  assert eng._admission.transitions >= 2     # up AND back down
  assert len(eng.finished) == n
  for i in range(n):
    if i in shed:
      assert eng.finished[i].new_tokens == 0
    else:
      assert eng.finished[i].finish_reason == "length"
      np.testing.assert_array_equal(
          eng.finished[i].tokens, _oracle(model, params, prompts[i], 4),
          err_msg=f"req {i}")
