"""Tests for the benchmark evidence log (bench.py's 0.0-MFU fix).

The driver's end-of-round `bench.py` run must never report 0.0 when a
healthy-window measurement exists on disk; these tests cover the record
store and the fallback-selection logic it feeds.
"""

import json
import os
import subprocess
import sys

from easyparallellibrary_tpu.utils import bench_evidence

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_append_and_latest(tmp_path):
  p = str(tmp_path / "ev.json")
  bench_evidence.append_record(
      {"metric": "m", "value": 0.4, "unix_time": 100}, path=p)
  bench_evidence.append_record(
      {"metric": "m", "value": 0.3, "unix_time": 200}, path=p)
  bench_evidence.append_record(
      {"metric": "other", "value": 9.9, "unix_time": 300}, path=p)
  rec = bench_evidence.latest_record("m", path=p)
  assert rec["value"] == 0.3  # latest by time, not highest
  assert bench_evidence.latest_record("absent", path=p) is None


def test_corrupt_file_preserved_aside(tmp_path):
  p = str(tmp_path / "ev.json")
  with open(p, "w") as f:
    f.write("{not json")
  assert bench_evidence.load_records(p) == []
  bench_evidence.append_record({"metric": "m", "value": 1.0}, path=p)
  assert len(bench_evidence.load_records(p)) == 1
  # The unparseable original must survive as a .corrupt-* sibling, not
  # be silently overwritten.
  corrupt = [f for f in os.listdir(tmp_path) if ".corrupt-" in f]
  assert len(corrupt) == 1
  with open(tmp_path / corrupt[0]) as f:
    assert f.read() == "{not json"


def test_timestamps_autofilled(tmp_path):
  p = str(tmp_path / "ev.json")
  bench_evidence.append_record({"metric": "m", "value": 1.0}, path=p)
  rec = bench_evidence.load_records(p)[0]
  assert rec["unix_time"] > 0 and rec["utc"].endswith("Z")


def test_bench_fallback_reports_evidence_not_zero(tmp_path):
  """bench.py with an exhausted probe budget must emit a NULL headline
  value with the evidence record's number under `last_known` (a stale
  MFU must be unquotable as a fresh measurement, VERDICT weak #6), with
  the raw data inline."""
  p = str(tmp_path / "ev.json")
  bench_evidence.append_record(
      {"metric": "gpt350m_train_mfu", "value": 0.51, "unit": "mfu",
       "raw": {"chain_times_s": [1.0]}, "config": {"batch": 16}}, path=p)
  env = dict(os.environ, EPL_BENCH_EVIDENCE=p,
             EPL_BENCH_PROBE_BUDGET_S="1",
             # Force an unreachable platform: CPU mode would make the
             # probe succeed, so point JAX at the (possibly wedged)
             # default backend with a 1s budget — if the backend happens
             # to be healthy the probe returns True and this test cannot
             # assert the fallback, so instead force the probe to fail
             # by giving jax a nonexistent platform.
             JAX_PLATFORMS="nonexistent")
  out = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, env=env, timeout=120)
  line = out.stdout.strip().splitlines()[-1]
  result = json.loads(line)
  assert result["value"] is None
  assert result["vs_baseline"] is None
  assert result["stale"] is True
  assert result["last_known"] == 0.51
  assert result["last_known_vs_baseline"] == round(0.51 / 0.40, 4)
  assert result["detail"]["fallback"] == "evidence"
  assert result["detail"]["raw"] == {"chain_times_s": [1.0]}
