"""Explicit-collective DP path vs the implicit GSPMD path."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu import ops
from easyparallellibrary_tpu.parallel import (
    TrainState, create_sharded_train_state, make_train_step, parallelize)
from easyparallellibrary_tpu.parallel.explicit import (
    make_explicit_dp_train_step)


class Net(nn.Module):
  @nn.compact
  def __call__(self, x):
    return ops.Dense(1, parallel="none")(jnp.tanh(
        ops.Dense(16, parallel="none")(x)))


def _setup(config=None):
  env = epl.init(config)
  mesh = epl.current_plan().build_mesh()
  model = Net()
  r = np.random.RandomState(0)
  x = jnp.asarray(r.randn(16, 8), jnp.float32)
  y = jnp.asarray(r.randn(16, 1), jnp.float32)

  def loss_fn(params, batch, rng):
    pred = model.apply({"params": params}, batch["x"])
    return jnp.mean((pred - batch["y"]) ** 2), {}

  tx = optax.sgd(0.1)

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, x)["params"], tx=tx)

  return env, mesh, model, loss_fn, init_fn, {"x": x, "y": y}


def _run_explicit(config=None, steps=5):
  env, mesh, model, loss_fn, init_fn, batch = _setup(config)
  state = init_fn(jax.random.PRNGKey(0))
  step = make_explicit_dp_train_step(loss_fn, mesh, config=env.config)
  losses = []
  for _ in range(steps):
    state, m = step(state, batch, jax.random.PRNGKey(1))
    losses.append(float(m["loss"]))
  return losses


def _run_implicit(steps=5):
  env, mesh, model, loss_fn, init_fn, batch = _setup()
  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  step = parallelize(make_train_step(loss_fn), mesh, shardings)
  losses = []
  for _ in range(steps):
    state, m = step(state, batch, jax.random.PRNGKey(1))
    losses.append(float(m["loss"]))
  return losses


def test_explicit_matches_implicit():
  np.testing.assert_allclose(_run_explicit(), _run_implicit(),
                             rtol=1e-5, atol=1e-7)


def test_explicit_with_tiny_buckets_and_compression():
  cfg = epl.Config({"communication.fusion_threshold_mb": 1,
                    "communication.max_splits": 2,
                    "communication.compress_dtype": "bf16"})
  # bf16 wire loses precision but must stay close and still train.
  explicit = _run_explicit(cfg)
  implicit = _run_implicit()
  np.testing.assert_allclose(explicit, implicit, rtol=5e-2)
  assert explicit[-1] < explicit[0]


def test_explicit_sum_reduction():
  cfg = epl.Config({"communication.gradients_reduce_method": "sum"})
  losses = _run_explicit(cfg)
  # Sum-reduction scales grads by the DP degree: faster (here unstable-r)
  # movement, but still finite and different from mean.
  assert np.isfinite(losses).all()
  assert not np.allclose(losses, _run_implicit())
