"""Pallas flash attention tests (interpreter mode on CPU; same code runs
compiled on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from easyparallellibrary_tpu.kernels import flash_attention


def _full_attention(q, k, v, causal=True):
  B, S, H, D = q.shape
  scale = 1.0 / np.sqrt(D)
  scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
  if causal:
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
    scores = jnp.where(mask[None, None], scores, -1e30)
  probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
  return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _qkv(B=2, S=128, H=2, D=32, seed=0):
  r = np.random.RandomState(seed)
  mk = lambda: jnp.asarray(r.randn(B, S, H, D), jnp.float32)
  return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_full(causal):
  q, k, v = _qkv()
  out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
  ref = _full_attention(q, k, v, causal=causal)
  np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)


def test_flash_multiblock():
  q, k, v = _qkv(S=256, seed=1)
  out = flash_attention(q, k, v, causal=True, block_q=64, block_k=128)
  ref = _full_attention(q, k, v, causal=True)
  np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_grads_match(causal):
  q, k, v = _qkv(S=64, seed=2)

  def loss_flash(q, k, v):
    return jnp.mean(flash_attention(q, k, v, causal=causal,
                                    block_q=32, block_k=32) ** 2)

  def loss_full(q, k, v):
    return jnp.mean(_full_attention(q, k, v, causal=causal) ** 2)

  g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
  g2 = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
  for a, b in zip(g1, g2):
    np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_streaming_path_matches_full(causal, monkeypatch):
  """Force the long-sequence streaming kernels (grid-streamed KV with
  VMEM scratch accumulators) at test size and check against full
  attention — the resident/streaming dispatch must be invisible."""
  import importlib
  fa_mod = importlib.import_module(
      "easyparallellibrary_tpu.kernels.flash_attention")
  monkeypatch.setattr(fa_mod, "_RESIDENT_MAX_BYTES", 1)
  q, k, v = _qkv(S=256, seed=4)
  out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
  ref = _full_attention(q, k, v, causal=causal)
  np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_streaming_grads_match(causal, monkeypatch):
  import importlib
  fa_mod = importlib.import_module(
      "easyparallellibrary_tpu.kernels.flash_attention")
  monkeypatch.setattr(fa_mod, "_RESIDENT_MAX_BYTES", 1)
  q, k, v = _qkv(S=128, seed=5)

  def loss_flash(q, k, v):
    return jnp.mean(flash_attention(q, k, v, causal=causal,
                                    block_q=32, block_k=32) ** 2)

  def loss_full(q, k, v):
    return jnp.mean(_full_attention(q, k, v, causal=causal) ** 2)

  g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
  g2 = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
  for a, b in zip(g1, g2):
    np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-5)


def test_flash_streaming_uneven_blocks(monkeypatch):
  """Streaming path with block_q != block_k exercises the causal
  index-map clamps on both grids."""
  import importlib
  fa_mod = importlib.import_module(
      "easyparallellibrary_tpu.kernels.flash_attention")
  monkeypatch.setattr(fa_mod, "_RESIDENT_MAX_BYTES", 1)
  q, k, v = _qkv(S=256, seed=6)

  def loss(attn):
    return jax.grad(lambda a, b, c: jnp.mean(attn(a, b, c) ** 2),
                    argnums=(0, 1, 2))(q, k, v)

  g1 = loss(lambda a, b, c: flash_attention(a, b, c, causal=True,
                                            block_q=32, block_k=64))
  g2 = loss(lambda a, b, c: _full_attention(a, b, c, causal=True))
  for a, b in zip(g1, g2):
    np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-5)


def test_flash_small_seq_single_block():
  q, k, v = _qkv(S=16, seed=3)
  out = flash_attention(q, k, v, causal=True)
  ref = _full_attention(q, k, v, causal=True)
  np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)


def test_flash_indivisible_raises():
  q, k, v = _qkv(S=96)
  with pytest.raises(ValueError):
    flash_attention(q, k, v, block_q=64, block_k=64)


def test_gpt_with_pallas_flash_matches_xla():
  import easyparallellibrary_tpu as epl
  from easyparallellibrary_tpu.models import GPT, GPTConfig

  epl.init()
  base = dict(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
              d_ff=64, max_seq_len=32, dtype=jnp.float32)
  flash_model = GPT(GPTConfig(**base, attn_impl="pallas_flash"))
  xla_model = GPT(GPTConfig(**base, attn_impl="xla"))
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 32)),
                    jnp.int32)
  params = flash_model.init(jax.random.PRNGKey(0), ids)["params"]
  out_flash = flash_model.apply({"params": params}, ids)
  out_xla = xla_model.apply({"params": params}, ids)
  np.testing.assert_allclose(out_flash, out_xla, rtol=2e-4, atol=2e-5)


def _ref_with_lse(q, k, v, causal=True):
  B, S, H, D = q.shape
  s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(D)
  if causal:
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
    s = jnp.where(mask[None, None], s, -1e30)
  lse = jax.nn.logsumexp(s, axis=-1)                        # [B, H, S]
  p = jnp.exp(s - lse[..., None])
  o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)
  return o, lse.transpose(0, 2, 1)                          # [B, S, H]


@pytest.mark.parametrize("causal", [True, False])
def test_flash_lse_matches_full(causal):
  from easyparallellibrary_tpu.kernels.flash_attention import (
      flash_attention_lse)
  q, k, v = _qkv(S=64, seed=7)
  o1, l1 = flash_attention_lse(q, k, v, causal=causal)
  o2, l2 = _ref_with_lse(q, k, v, causal=causal)
  np.testing.assert_allclose(o1, o2, rtol=2e-5, atol=2e-6)
  np.testing.assert_allclose(l1, l2, rtol=2e-5, atol=2e-6)


def test_flash_lse_cotangent_grads():
  """The lse output is differentiable: its cotangent folds into the
  kernel's delta term (ds = p*(dp - delta + dlse)); this is what the
  ring-attention merge relies on."""
  from easyparallellibrary_tpu.kernels.flash_attention import (
      flash_attention_lse)
  q, k, v = _qkv(S=32, D=16, seed=9)

  def loss_flash(q, k, v):
    o, l = flash_attention_lse(q, k, v, causal=True)
    return jnp.sum(o ** 2) + jnp.sum(jnp.sin(l))

  def loss_ref(q, k, v):
    o, l = _ref_with_lse(q, k, v, causal=True)
    return jnp.sum(o ** 2) + jnp.sum(jnp.sin(l))

  g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
  g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
  for a, b in zip(g1, g2):
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_unknown_attn_impl_raises():
  import easyparallellibrary_tpu as epl
  from easyparallellibrary_tpu.models import GPT, GPTConfig
  epl.init()
  model = GPT(GPTConfig(vocab_size=64, num_layers=1, num_heads=2,
                        d_model=16, d_ff=32, max_seq_len=16,
                        attn_impl="flash"))  # typo for pallas_flash
  ids = jnp.zeros((1, 16), jnp.int32)
  with pytest.raises(ValueError, match="attn_impl"):
    model.init(jax.random.PRNGKey(0), ids)


def test_block_autotune_table_overrides_heuristic():
  """VERDICT r3 item 6 infrastructure: _default_block consults the
  autotuned (S, d, itemsize) table (written by
  benchmarks/flash_autotune.py on hardware) and keeps the 512/1024
  heuristic for unswept shapes."""
  import importlib
  fa = importlib.import_module(
      "easyparallellibrary_tpu.kernels.flash_attention")
  try:
    assert fa._default_block(4096, d=64) == 512        # resident regime
    assert fa._default_block(16384, d=64) == 1024      # streaming regime
    fa.set_block_want(4096, 64, 2, 2048)
    assert fa._default_block(4096, d=64) == 2048       # tuned override
    assert fa._default_block(4096, d=64, itemsize=4) == 512  # other key
    # Explicit want still wins over the table.
    assert fa._default_block(4096, 256, d=64) == 256
  finally:
    fa._BLOCK_TABLE.pop((4096, 64, 2), None)
