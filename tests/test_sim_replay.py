"""Golden replay-fidelity pin — the simulator subsystem's anchor.

The recorded REAL-fleet chaos-heal episode (benchmarks/sim_golden.py
-> tests/golden/sim_chaos_heal.json) must replay in the simulator to
the IDENTICAL actuation sequence: same actuators, same knob
transitions, same order.  This is what licenses using the simulator
for policy search at 100-1000-replica scale (docs/simulator.md) —
the policies are the real objects, and this pin proves the modeled
physics feeds them the same decision stream the real fleet produced.
"""

import pytest

from easyparallellibrary_tpu.sim import replay as replay_lib


@pytest.mark.quick
def test_replay_matches_recorded_chaos_heal_episode():
  """The simulator replays the recorded real-fleet chaos-heal episode
  to the identical actuation sequence — and the same shed / sweep /
  breach counts, which pins the record streams the decisions were made
  FROM, not just the decisions."""
  golden = replay_lib.load_golden()
  out = replay_lib.replay(golden)
  assert out["sequence"] == golden["sequence"]
  assert out["shed"] == golden["counters"]["shed"]
  assert out["busy_sweeps"] == golden["counters"]["busy_sweeps"]
  assert out["breaches"] == golden["counters"]["breaches"]
  assert out["recoveries"] == golden["counters"]["recoveries"]
  assert out["replicas_peak"] == golden["counters"]["replicas_peak"]


def test_golden_episode_is_nontrivial():
  """Guard against the golden file degrading into a no-op episode: the
  fidelity claim is only interesting if the recorded episode actually
  exercised breach -> escalate -> scale -> recover -> de-escalate."""
  golden = replay_lib.load_golden()
  seq = golden["sequence"]
  actuators = {e["actuator"] for e in seq}
  assert {"autoscale", "autotune"} <= actuators
  assert golden["counters"]["shed"] > 0
  assert golden["counters"]["breaches"] > 0
  assert golden["counters"]["recoveries"] > 0
  assert golden["counters"]["replicas_peak"] > golden["num_replicas"]


def test_replay_is_itself_deterministic():
  golden = replay_lib.load_golden()
  a = replay_lib.replay(golden)
  b = replay_lib.replay(golden)
  assert a["sequence"] == b["sequence"]
  assert a["shed"] == b["shed"]


def test_replay_unaffected_by_reactor_knob():
  """ISSUE 19 regression: the simulator drives the fleet through the
  sweep-compat ``router.step()`` path, so turning on the reactor
  (``serving.router.reactor`` — the readiness-driven run()/front-door
  driver, serving/reactor.py) must not perturb the golden episode:
  the actuation sequence replays event-for-event identical."""
  golden = replay_lib.load_golden()
  baseline = replay_lib.replay(golden)
  reactored = dict(golden)
  reactored["config"] = {**golden["config"]}
  serving = dict(reactored["config"].get("serving", {}))
  serving["router"] = {**serving.get("router", {}), "reactor": True}
  reactored["config"]["serving"] = serving
  out = replay_lib.replay(reactored)
  assert out["sequence"] == baseline["sequence"] == golden["sequence"]
  assert out["shed"] == baseline["shed"]
  assert out["busy_sweeps"] == baseline["busy_sweeps"]
  assert out["breaches"] == baseline["breaches"]
  assert out["recoveries"] == baseline["recoveries"]
  assert out["replicas_peak"] == baseline["replicas_peak"]
