"""Config system tests (reference analog: tests/config_test.py —
precedence, typing, typo rejection)."""

import os

import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.config import Config


def test_defaults():
  c = Config()
  assert c.pipeline.num_micro_batch == 1
  assert c.communication.fusion_threshold_mb == 32
  assert c.communication.num_communicators == 2
  assert c.zero.level == ""
  assert c.cluster.colocate_split_and_replicate is True


def test_dotted_overrides():
  c = Config({"pipeline.num_micro_batch": 4, "zero.level": "v1"})
  assert c.pipeline.num_micro_batch == 4
  assert c.zero.level == "v1"


def test_nested_overrides():
  c = Config({"pipeline": {"num_micro_batch": 8, "num_stages": 2}})
  assert c.pipeline.num_micro_batch == 8
  assert c.pipeline.num_stages == 2


def test_env_var_overrides_default_but_dict_wins(monkeypatch):
  # Reference precedence: python dict > env var > default
  # (epl/config.py:289-299).
  monkeypatch.setenv("EPL_PIPELINE_NUM_MICRO_BATCH", "16")
  c = Config()
  assert c.pipeline.num_micro_batch == 16
  c2 = Config({"pipeline.num_micro_batch": 2})
  assert c2.pipeline.num_micro_batch == 2


def test_env_var_bool_coercion(monkeypatch):
  monkeypatch.setenv("EPL_IO_SLICING", "true")
  assert Config().io.slicing is True
  monkeypatch.setenv("EPL_IO_SLICING", "0")
  assert Config().io.slicing is False


def test_unknown_key_rejected():
  # Reference: __setattr__ rejects unknown keys (epl/config.py:49-53).
  with pytest.raises(ValueError):
    Config({"pipeline.num_micro_batches": 4})  # typo'd key
  with pytest.raises(ValueError):
    Config({"nonexistent.thing": 1})
  c = Config()
  with pytest.raises(AttributeError):
    c.pipeline.num_micro_batchs = 4


def test_setattr_type_coercion():
  c = Config()
  c.pipeline.num_micro_batch = "8"
  assert c.pipeline.num_micro_batch == 8


def test_validation():
  with pytest.raises(ValueError):
    Config({"zero.level": "v2"})  # v2 unimplemented in the reference too
  with pytest.raises(ValueError):
    Config({"amp.level": "O3"})
  with pytest.raises(ValueError):
    Config({"pipeline.num_micro_batch": 0})
  with pytest.raises(ValueError):
    Config({"sequence.parallelism": "rings"})


def test_categories_frozen():
  c = Config()
  with pytest.raises(AttributeError):
    c.pipeline = None
