"""Aux subsystem tests: native/python IO, io slicing, profiler, launcher,
metric merge (reference analogs: estimator_dp_example.py IO tests,
profiler tests, test_launcher.sh)."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.constants import GraphKeys
from easyparallellibrary_tpu.io import (
    RecordReader, native_io_available, shard_files, write_records)
from easyparallellibrary_tpu.parallel.metrics import (
    collect_merged, merge_shard_metrics)
from easyparallellibrary_tpu.profiler import (
    FlopsProfiler, StepProfiler, compiled_cost, compiled_memory,
    estimate_mfu)


# ---------------------------------------------------------------- IO ----

def _make_files(tmp_path, n_files=4, recs_per_file=5):
  files = []
  for i in range(n_files):
    path = str(tmp_path / f"data_{i}.rec")
    write_records(path, [f"file{i}_rec{j}".encode()
                         for j in range(recs_per_file)])
    files.append(path)
  return files


def test_native_io_built():
  assert native_io_available(), "run `make build` to compile csrc/"


@pytest.mark.parametrize("use_native", [True, False])
def test_record_roundtrip(tmp_path, use_native):
  files = _make_files(tmp_path)
  reader = RecordReader(files, use_native=use_native)
  got = [r.decode() for r in reader]
  expected = [f"file{i}_rec{j}" for i in range(4) for j in range(5)]
  assert got == expected


def test_native_matches_python_reader(tmp_path):
  files = _make_files(tmp_path, n_files=3, recs_per_file=7)
  native = [r for r in RecordReader(files, use_native=True)]
  python = [r for r in RecordReader(files, use_native=False)]
  assert native == python


@pytest.mark.parametrize("use_native", [True, False])
def test_reader_sharding(tmp_path, use_native):
  # Contiguous proportional slicing (reference io_slicing semantics).
  files = _make_files(tmp_path, n_files=4)
  shard0 = [r.decode() for r in RecordReader(
      files, shard_index=0, num_shards=2, use_native=use_native)]
  shard1 = [r.decode() for r in RecordReader(
      files, shard_index=1, num_shards=2, use_native=use_native)]
  assert all(r.startswith(("file0", "file1")) for r in shard0)
  assert all(r.startswith(("file2", "file3")) for r in shard1)
  assert len(shard0) + len(shard1) == 20


def test_native_reader_streams_bounded_memory(tmp_path):
  """A file far larger than the prefetch budget must not be resident all
  at once: the reader streams records through bounded queues (round-1
  weak item 3 — the old design preloaded whole files).  Reads a few
  records from a ~64MB file with prefetch=8 and checks the process RSS
  grew by much less than the file size."""
  if not native_io_available():
    pytest.skip("native IO not built")

  def rss_mb():
    with open("/proc/self/status") as f:
      for line in f:
        if line.startswith("VmRSS:"):
          return int(line.split()[1]) / 1024.0
    return 0.0

  path = str(tmp_path / "big.rec")
  payload = b"x" * 65536                      # 64KB per record
  write_records(path, [payload] * 1024)       # ~64MB file

  before = rss_mb()
  reader = RecordReader([path], use_native=True, prefetch_records=8)
  it = iter(reader)
  got = [next(it) for _ in range(16)]
  grown = rss_mb() - before
  assert all(r == payload for r in got)
  # Budget: 8-record main queue + per-file staging (≥4) ≈ <2MB of
  # records; allow generous allocator slack but far below the 64MB file.
  assert grown < 32.0, f"RSS grew {grown:.1f}MB — whole file resident?"
  del it, reader


def test_large_record_grows_buffer(tmp_path):
  path = str(tmp_path / "big.rec")
  big = os.urandom(300_000)  # > initial 64KB buffer
  write_records(path, [b"small", big, b"tail"])
  got = list(RecordReader([path], use_native=True))
  assert got == [b"small", big, b"tail"]


def test_shard_files_proportional():
  epl.init()
  files = [f"f{i}" for i in range(10)]
  s0 = shard_files(files, 3, 0)
  s1 = shard_files(files, 3, 1)
  s2 = shard_files(files, 3, 2)
  assert s0 + s1 + s2 == files
  assert [len(s0), len(s1), len(s2)] == [4, 3, 3]


def test_shard_files_drop_last():
  epl.init(epl.Config({"io.drop_last_files": True}))
  files = [f"f{i}" for i in range(10)]
  shards = [shard_files(files, 3, i) for i in range(3)]
  assert [len(s) for s in shards] == [3, 3, 3]


def test_shard_files_validation():
  epl.init()
  with pytest.raises(ValueError):
    shard_files(["a"], 2, 2)


# ------------------------------------------------------------ profiler --

def test_compiled_cost_reports_flops():
  def f(x):
    return x @ x

  x = jnp.ones((128, 128))
  cost = compiled_cost(f, x)
  # 2 * 128^3 = 4.2M flops
  assert cost.get("flops", 0) >= 2 * 128 ** 3 * 0.5


def test_compiled_memory_reports_bytes():
  def f(x):
    return (x @ x).sum()

  mem = compiled_memory(f, jnp.ones((64, 64)))
  assert mem.get("argument_size_in_bytes", 0) >= 64 * 64 * 4


def test_step_profiler_summary():
  prof = StepProfiler(flops_per_step=1e9, tokens_per_step=1024, warmup=1)
  import time
  for _ in range(4):
    prof.tick()
    time.sleep(0.01)
  s = prof.summary()
  assert s["step_time_s"] > 0
  assert s["tokens_per_sec"] > 0
  assert 0 <= s["mfu"] < 10


def test_flops_profiler_measure():
  prof = FlopsProfiler(every_n_steps=2)
  flops = prof.measure_from(lambda x: x @ x, jnp.ones((64, 64)))
  assert flops > 0
  assert prof.step() is None  # first call only arms the timer
  assert prof.step() is None
  stats = prof.step()
  assert stats is not None and "mfu" in stats


# ------------------------------------------------------------- metrics --

def test_collection_merge_in_train_step():
  import optax
  from flax import linen as nn
  from easyparallellibrary_tpu import ops
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state, make_train_step, parallelize)

  env = epl.init()
  mesh = epl.current_plan().build_mesh()

  class Net(nn.Module):
    @nn.compact
    def __call__(self, x):
      return ops.Dense(1, parallel="none")(x)

  model = Net()
  x = jnp.ones((16, 4))
  y = jnp.zeros((16, 1))

  def loss_fn(params, batch, rng):
    pred = model.apply({"params": params}, batch["x"])
    err = pred - batch["y"]
    epl.add_to_collection(jnp.abs(err), GraphKeys.GLOBAL_MEAN_OBJECTS)
    epl.add_to_collection(jnp.abs(err), GraphKeys.GLOBAL_SUM_OBJECTS)
    return jnp.mean(err ** 2), {}

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, x)["params"],
                             tx=optax.sgd(0.1))

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  step = parallelize(make_train_step(loss_fn), mesh, shardings)
  state, metrics = step(state, {"x": x, "y": y}, jax.random.PRNGKey(1))
  mean_key = f"{GraphKeys.GLOBAL_MEAN_OBJECTS}_0"
  sum_key = f"{GraphKeys.GLOBAL_SUM_OBJECTS}_0"
  assert mean_key in metrics and sum_key in metrics
  np.testing.assert_allclose(float(metrics[sum_key]),
                             float(metrics[mean_key]) * 16, rtol=1e-5)


def test_merge_shard_metrics():
  shard_map = jax.shard_map
  from jax.sharding import PartitionSpec as P
  env = epl.init()
  mesh = env.cluster.build_mesh()

  def body(v):
    return merge_shard_metrics({"m": jnp.mean(v)}, "mean")["m"]

  f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P())
  out = f(jnp.arange(8.0))
  np.testing.assert_allclose(float(out), 3.5)


# ------------------------------------------------------------- launcher --

def test_launcher_local_multiprocess(tmp_path):
  """Two local processes bootstrap a shared JAX cluster
  (reference analog: tests/test_launcher.sh, 2 workers x 1 GPU)."""
  from easyparallellibrary_tpu.utils.launcher import launch_local
  script = tmp_path / "worker.py"
  script.write_text(
      "import os\n"
      "os.environ['XLA_FLAGS'] = "
      "'--xla_force_host_platform_device_count=2'\n"
      "import jax\n"
      "jax.config.update('jax_platforms', 'cpu')\n"
      "import sys; sys.path.insert(0, %r)\n"
      "from easyparallellibrary_tpu.utils.launcher import init_distributed\n"
      "init_distributed()\n"
      "assert jax.process_count() == 2, jax.process_count()\n"
      "assert len(jax.devices()) == 4\n"
      "print('worker', jax.process_index(), 'ok')\n"
      % os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
  code = launch_local(2, [sys.executable, str(script)],
                      retries=0, log_dir=str(tmp_path / "logs"))
  logs = "".join(
      open(os.path.join(tmp_path, "logs", f)).read()
      for f in os.listdir(tmp_path / "logs"))
  assert code == 0, logs
  assert "worker 0 ok" in logs and "worker 1 ok" in logs


def test_launcher_retry_on_failure(tmp_path):
  from easyparallellibrary_tpu.utils.launcher import launch_local
  script = tmp_path / "fail.py"
  script.write_text("import sys; sys.exit(3)\n")
  code = launch_local(1, [sys.executable, str(script)], retries=1)
  assert code == 1


def test_memory_profiler_records_csv_png(tmp_path):
  pytest.importorskip("matplotlib")   # optional dep: dump_png degrades
  from easyparallellibrary_tpu.profiler import MemoryProfiler
  prof = MemoryProfiler(every_n_steps=2)
  x = jnp.ones((64, 64))
  for _ in range(6):
    x = (x @ x) / 64.0
    prof.step()
  assert len(prof.records) == 3          # steps 2, 4, 6
  assert prof.peak_bytes() >= 0.0
  csv_path = str(tmp_path / "mem.csv")
  prof.dump_csv(csv_path)
  assert os.path.getsize(csv_path) > 0
  png_path = str(tmp_path / "mem.png")
  prof.dump_png(png_path, phase_spans=[(2, 4, "warmup")])
  assert os.path.exists(png_path) and os.path.getsize(png_path) > 0


def test_memory_profiler_empty_png_is_noop(tmp_path):
  from easyparallellibrary_tpu.profiler import MemoryProfiler
  prof = MemoryProfiler(every_n_steps=1)
  png_path = str(tmp_path / "none.png")
  prof.dump_png(png_path)
  assert not os.path.exists(png_path)
