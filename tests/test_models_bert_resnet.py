"""BERT + ResNet model tests (BASELINE configs 1-3 shapes)."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np
import optax

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import (
    Bert, BertConfig, ResNet, resnet18_config)
from easyparallellibrary_tpu.models.bert import bert_mlm_loss
from easyparallellibrary_tpu.parallel import (
    TrainState, create_sharded_train_state, make_train_step, parallelize)

BERT_TINY = BertConfig(vocab_size=128, num_layers=4, num_heads=4,
                       d_model=32, d_ff=64, max_seq_len=16,
                       dtype=jnp.float32)


def test_bert_forward_shape():
  model = Bert(BERT_TINY)
  ids = jnp.zeros((2, 8), jnp.int32)
  params = model.init(jax.random.PRNGKey(0), ids)["params"]
  logits = model.apply({"params": params}, ids)
  assert logits.shape == (2, 8, 128)


@pytest.mark.slow
def test_bert_pipeline_matches_sequential():
  import dataclasses
  env = epl.init()
  mesh = env.cluster.build_mesh(stage=2)
  cfg = dataclasses.replace(BERT_TINY, pipeline_stages=2, num_micro_batch=2)
  pp = Bert(cfg)
  seq = Bert(dataclasses.replace(cfg, pipeline_debug_sequential=True))
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (8, 16)),
                    jnp.int32)
  params = pp.init(jax.random.PRNGKey(0), ids)["params"]
  out_pp = jax.jit(lambda p: pp.apply({"params": p}, ids))(params)
  out_seq = jax.jit(lambda p: seq.apply({"params": p}, ids))(params)
  np.testing.assert_allclose(out_pp, out_seq, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_bert_mlm_training():
  env = epl.init()
  mesh = epl.current_plan().build_mesh()
  model = Bert(BERT_TINY)
  r = np.random.RandomState(0)
  ids = jnp.asarray(r.randint(0, 128, (8, 16)), jnp.int32)
  batch = {"ids": ids, "labels": ids,
           "mask": jnp.asarray(r.rand(8, 16) < 0.15, jnp.float32)}
  tx = optax.adam(1e-3)

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, ids)["params"], tx=tx)

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  step = parallelize(
      make_train_step(lambda p, b, rr: bert_mlm_loss(model, p, b, rr)),
      mesh, shardings)
  losses = []
  for _ in range(8):
    state, m = step(state, batch, jax.random.PRNGKey(1))
    losses.append(float(m["loss"]))
  assert losses[-1] < losses[0]


@pytest.mark.slow
def test_resnet_dp_training_with_split_head():
  env = epl.init()
  with epl.split(2):
    pass
  mesh = epl.current_plan().build_mesh()
  cfg = resnet18_config(num_classes=64, dtype=jnp.float32)

  class WithSplitHead(ResNet):
    pass

  model = ResNet(cfg)
  x = jnp.asarray(np.random.RandomState(0).randn(8, 32, 32, 3), jnp.float32)
  y = jnp.asarray(np.random.RandomState(1).randint(0, 64, (8,)), jnp.int32)

  def make_model_apply(params, inputs):
    with epl.split(2):
      return model.apply({"params": params}, inputs)

  tx = optax.adam(1e-3)

  def init_fn(rng):
    with epl.split(2):
      params = model.init(rng, x[:1])["params"]
    return TrainState.create(apply_fn=model.apply, params=params, tx=tx)

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  # Head kernel is column-parallel over the 2-way model axis.
  head = state.params["head"]["kernel"]
  assert head.names == (None, "model")

  from easyparallellibrary_tpu import ops

  def loss_fn(params, batch, rng):
    logits = make_model_apply(params, batch["x"])
    loss = ops.distributed_sparse_softmax_cross_entropy_with_logits(
        batch["y"], logits)
    return jnp.mean(loss), {}

  step = parallelize(make_train_step(loss_fn), mesh, shardings)
  losses = []
  for _ in range(16):  # early steps are noisy (GroupNorm + Adam warmup)
    state, m = step(state, {"x": x, "y": y}, jax.random.PRNGKey(2))
    losses.append(float(m["loss"]))
  assert losses[-1] < losses[0]


@pytest.mark.slow
def test_bert_qa_head_trains():
  from easyparallellibrary_tpu.models.bert import (
      BertForQuestionAnswering, bert_qa_loss)
  env = epl.init()
  mesh = epl.current_plan().build_mesh()
  model = BertForQuestionAnswering(BERT_TINY)
  r = np.random.RandomState(0)
  ids = jnp.asarray(r.randint(0, 128, (8, 16)), jnp.int32)
  batch = {"ids": ids,
           "start_positions": jnp.asarray(r.randint(0, 16, (8,)), jnp.int32),
           "end_positions": jnp.asarray(r.randint(0, 16, (8,)), jnp.int32)}

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, ids)["params"],
                             tx=optax.adam(1e-3))

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  step = parallelize(
      make_train_step(lambda p, b, rr: bert_qa_loss(model, p, b, rr)),
      mesh, shardings)
  losses = []
  for _ in range(8):
    state, m = step(state, batch, jax.random.PRNGKey(1))
    losses.append(float(m["loss"]))
  assert losses[-1] < losses[0]


@pytest.mark.slow
def test_resnet_batchnorm_variant_trains():
  """norm="batch" ResNet: BatchNorm stats live in a mutable collection
  carried by MutableTrainState; under GSPMD the (data-sharded) batch
  statistics are global-batch statistics.  NOTES round-1 deferred item."""
  from easyparallellibrary_tpu.models.resnet import ResNetConfig
  from easyparallellibrary_tpu.parallel import (
      MutableTrainState, make_mutable_train_step)

  epl.init()
  with epl.replicate(1):
    pass
  mesh = epl.current_plan().build_mesh()
  cfg = ResNetConfig(stage_sizes=(1, 1), num_filters=8, num_classes=8,
                     dtype=jnp.float32, norm="batch")
  model = ResNet(cfg)
  x = jnp.asarray(np.random.RandomState(0).randn(8, 16, 16, 3), jnp.float32)
  y = jnp.asarray(np.random.RandomState(1).randint(0, 8, (8,)), jnp.int32)
  tx = optax.adam(3e-3)

  def init_fn(rng):
    variables = model.init(rng, x[:1], train=True)
    return MutableTrainState.create(
        apply_fn=model.apply, params=variables["params"], tx=tx,
        model_state={"batch_stats": variables["batch_stats"]})

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))

  def loss_fn(params, model_state, batch, rng):
    logits, new_state = model.apply(
        {"params": params, **model_state}, batch["x"], train=True,
        mutable=["batch_stats"])
    loss = jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["y"]))
    return loss, ({}, new_state)

  step = parallelize(make_mutable_train_step(loss_fn), mesh, shardings)
  stats0 = jax.tree_util.tree_map(
      np.asarray, state.model_state["batch_stats"])
  losses = []
  for _ in range(8):
    state, m = step(state, {"x": x, "y": y}, jax.random.PRNGKey(1))
    losses.append(float(m["loss"]))
  assert losses[-1] < losses[0]
  # Running stats actually moved.
  moved = jax.tree_util.tree_map(
      lambda a, b: float(jnp.max(jnp.abs(a - b))), stats0,
      jax.tree_util.tree_map(np.asarray, state.model_state["batch_stats"]))
  assert max(jax.tree_util.tree_leaves(moved)) > 1e-6
  # Eval path: running averages, no mutation.
  logits = model.apply(
      {"params": state.params, **state.model_state}, x, train=False)
  assert np.isfinite(np.asarray(logits)).all()


def test_resnet_unknown_norm_raises():
  from easyparallellibrary_tpu.models.resnet import ResNetConfig
  epl.init()
  model = ResNet(ResNetConfig(stage_sizes=(1,), num_filters=8,
                              num_classes=4, norm="layer"))
  x = jnp.zeros((1, 16, 16, 3), jnp.float32)
  with pytest.raises(ValueError, match="norm"):
    model.init(jax.random.PRNGKey(0), x)


def test_bert_flash_attention_matches_xla():
  epl.init()
  base = dict(vocab_size=256, num_layers=2, num_heads=4, d_model=64,
              d_ff=128, max_seq_len=32, dtype=jnp.float32)
  flash = Bert(BertConfig(**base, attn_impl="pallas_flash"))
  xla = Bert(BertConfig(**base, attn_impl="xla"))
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 32)),
                    jnp.int32)
  params = flash.init(jax.random.PRNGKey(0), ids)["params"]
  out_f = flash.apply({"params": params}, ids)
  out_x = xla.apply({"params": params}, ids)
  np.testing.assert_allclose(out_f, out_x, rtol=2e-4, atol=2e-5)


def test_bert_unknown_attn_impl_raises():
  epl.init()
  model = Bert(BertConfig(vocab_size=64, num_layers=1, num_heads=2,
                          d_model=16, d_ff=32, max_seq_len=16,
                          attn_impl="flash"))
  ids = jnp.zeros((1, 16), jnp.int32)
  with pytest.raises(ValueError, match="attn_impl"):
    model.init(jax.random.PRNGKey(0), ids)


def _bert_mlm_batch(B, S, V, masked_per_sample=2):
  r = np.random.RandomState(0)
  ids = jnp.asarray(r.randint(0, V, (B, S)), jnp.int32)
  labels = jnp.asarray(r.randint(0, V, (B, S)), jnp.int32)
  # Equal mask count per sample: the smap engine averages per-micro-batch
  # masked means, which equals the global ratio exactly only then.
  mask = np.zeros((B, S), np.float32)
  for i in range(B):
    mask[i, r.choice(S, masked_per_sample, replace=False)] = 1.0
  return {"ids": ids, "labels": labels, "mask": jnp.asarray(mask)}


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
@pytest.mark.slow
def test_bert_smap_matches_sequential(schedule):
  """The shard_map pipeline engines drive BERT too (round 4: the engine
  is framework infrastructure, not a GPT special case) — loss and grads
  match the sequential ground truth."""
  from easyparallellibrary_tpu.models.bert import make_bert_smap_grad_fn

  env = epl.init()
  mesh = env.cluster.build_mesh(stage=2)
  base = dict(vocab_size=64, num_layers=4, num_heads=2, d_model=16,
              d_ff=32, max_seq_len=8, dtype=jnp.float32,
              pipeline_stages=2, num_micro_batch=4)
  pp = Bert(BertConfig(**base))
  batch = _bert_mlm_batch(16, 8, 64)
  params = pp.init(jax.random.PRNGKey(0), batch["ids"])["params"]
  seq = Bert(BertConfig(**base, pipeline_debug_sequential=True))

  g_smap = make_bert_smap_grad_fn(pp, mesh, schedule=schedule)
  (l1, _), g1 = jax.jit(lambda p: g_smap(p, batch, None))(params)
  l2, g2 = jax.jit(jax.value_and_grad(
      lambda p: bert_mlm_loss(seq, p, batch)[0]))(params)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=5e-3, atol=1e-5),
      g1, g2)


@pytest.mark.slow
def test_bert_smap_interleaved_matches_sequential():
  """Megatron-interleaved 1F1B for BERT (VERDICT r4 item 6): K=2 virtual
  chunks via the SHARED K-pass stacking helpers — loss and grads match
  the sequential ground truth."""
  from easyparallellibrary_tpu.models.bert import make_bert_smap_grad_fn

  env = epl.init()
  mesh = env.cluster.build_mesh(stage=2)
  base = dict(vocab_size=64, num_layers=4, num_heads=2, d_model=16,
              d_ff=32, max_seq_len=8, dtype=jnp.float32,
              pipeline_stages=2, num_micro_batch=4,
              pipeline_interleave=2)
  pp = Bert(BertConfig(**base))
  batch = _bert_mlm_batch(16, 8, 64)
  params = pp.init(jax.random.PRNGKey(0), batch["ids"])["params"]
  seq = Bert(BertConfig(**base, pipeline_debug_sequential=True))

  g_smap = make_bert_smap_grad_fn(pp, mesh)   # 1f1b auto-upgrades, K=2
  (l1, _), g1 = jax.jit(lambda p: g_smap(p, batch, None))(params)
  l2, g2 = jax.jit(jax.value_and_grad(
      lambda p: bert_mlm_loss(seq, p, batch)[0]))(params)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=5e-3, atol=1e-5),
      g1, g2)


@pytest.mark.slow
def test_bert_smap_config_dispatch_trains():
  """pipeline.engine="smap" dispatches BERT through
  make_bert_train_step; loss decreases."""
  from easyparallellibrary_tpu.models.bert import make_bert_train_step
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state, parallelize)

  env = epl.init(epl.Config({"pipeline.engine": "smap"}))
  cfg = BertConfig(vocab_size=64, num_layers=4, num_heads=2, d_model=16,
                   d_ff=32, max_seq_len=8, dtype=jnp.float32,
                   pipeline_stages=2, num_micro_batch=4)
  with epl.replicate(1):
    model = Bert(cfg)
  mesh = env.cluster.build_mesh(stage=2)
  batch = _bert_mlm_batch(16, 8, 64)

  def init_fn(rng):
    return TrainState.create(
        apply_fn=model.apply,
        params=model.init(rng, batch["ids"])["params"],
        tx=optax.adam(1e-2))

  state, sh = create_sharded_train_state(init_fn, mesh,
                                         jax.random.PRNGKey(0))
  step = parallelize(make_bert_train_step(model), mesh, sh)
  losses = []
  for i in range(4):
    state, m = step(state, batch, jax.random.PRNGKey(i))
    losses.append(float(m["loss"]))
  assert all(np.isfinite(l) for l in losses) and losses[-1] < losses[0]


@pytest.mark.slow
def test_bert_smap_zero_v1_matches_baseline():
  """ZeRO-1 rides the BERT smap wiring too (shared zero1_grad_layout):
  same trajectory as the plain engine, reduce-scatter in the program."""
  from easyparallellibrary_tpu.models.bert import make_bert_train_step
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state, parallelize)

  def run(zero_level):
    conf = {"pipeline.engine": "smap"}
    if zero_level:
      conf["zero.level"] = zero_level
    env = epl.init(epl.Config(conf))
    cfg = BertConfig(vocab_size=64, num_layers=4, num_heads=2, d_model=16,
                     d_ff=32, max_seq_len=8, dtype=jnp.float32,
                     pipeline_stages=2, num_micro_batch=4)
    with epl.replicate(1):
      model = Bert(cfg)
    mesh = env.cluster.build_mesh(stage=2)
    batch = _bert_mlm_batch(16, 8, 64)

    def init_fn(rng):
      return TrainState.create(
          apply_fn=model.apply,
          params=model.init(rng, batch["ids"])["params"],
          tx=optax.adam(1e-2))

    state, sh = create_sharded_train_state(
        init_fn, mesh, jax.random.PRNGKey(0), zero_level=zero_level)
    step = parallelize(make_bert_train_step(model), mesh, sh)
    losses = []
    for i in range(3):
      state, m = step(state, batch, jax.random.PRNGKey(i))
      losses.append(float(m["loss"]))
    if zero_level:
      txt = step.jitted.lower(state, batch,
                              jax.random.PRNGKey(9)).as_text()
      assert "reduce-scatter" in txt or "reduce_scatter" in txt
    return losses

  np.testing.assert_allclose(run("v1"), run(""), rtol=2e-5)


def _ragged_mlm_batch(B, S, V, masked_per_sample=3):
  r = np.random.RandomState(0)
  ids = jnp.asarray(r.randint(0, V, (B, S)), jnp.int32)
  labels = jnp.asarray(r.randint(0, V, (B, S)), jnp.int32)
  # Random mask POSITIONS: seq shards see ragged counts (the smap
  # emit's ratio-of-sums over seq must handle this exactly).
  mask = np.zeros((B, S), np.float32)
  for i in range(B):
    mask[i, r.choice(S, masked_per_sample, replace=False)] = 1.0
  return {"ids": ids, "labels": labels, "mask": jnp.asarray(mask)}


@pytest.mark.slow
def test_bert_ring_attention_matches_xla():
  """Bidirectional ring attention on the encoder (long-context parity
  with GPT): logits match the xla-attention model on a seq mesh."""
  env = epl.init(epl.Config({"sequence.parallelism": "ring",
                             "sequence.axis_size": 4,
                             "sequence.ring_impl": "dense"}))
  epl.current_plan().build_mesh()
  base = dict(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
              d_ff=64, max_seq_len=32, dtype=jnp.float32,
              seq_parallel=True)
  ring = Bert(BertConfig(**base, attn_impl="ring"))
  xla = Bert(BertConfig(**base, attn_impl="xla"))
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4, 32)),
                    jnp.int32)
  params = ring.init(jax.random.PRNGKey(0), ids)["params"]
  out_r = jax.jit(lambda p: ring.apply({"params": p}, ids))(params)
  out_x = jax.jit(lambda p: xla.apply({"params": p}, ids))(params)
  np.testing.assert_allclose(out_r, out_x, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_bert_smap_sequence_parallel_matches_sequential(impl):
  """The encoder family composes with sequence parallelism on the smap
  engine exactly like GPT (round 5): stage2 x seq2, ragged per-shard
  mask counts, loss and grads match the sequential ground truth."""
  from easyparallellibrary_tpu.models.bert import make_bert_smap_grad_fn

  env = epl.init(epl.Config({"sequence.ring_impl": "dense",
                             "sequence.ulysses_impl": "einsum"}))
  mesh = env.cluster.build_mesh(stage=2, seq=2)
  base = dict(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
              d_ff=64, max_seq_len=16, dtype=jnp.float32,
              seq_parallel=True, attn_impl=impl,
              pipeline_stages=2, num_micro_batch=2)
  pp = Bert(BertConfig(**base))
  batch = _ragged_mlm_batch(8, 16, 64)
  params = pp.init(jax.random.PRNGKey(0), batch["ids"])["params"]
  seq = Bert(BertConfig(**base, pipeline_debug_sequential=True))

  g_smap = make_bert_smap_grad_fn(pp, mesh)
  (l1, _), g1 = jax.jit(lambda p: g_smap(p, batch, None))(params)
  l2, g2 = jax.jit(jax.value_and_grad(
      lambda p: bert_mlm_loss(seq, p, batch)[0]))(params)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=5e-3, atol=1e-5),
      g1, g2)


@pytest.mark.slow
def test_bert_smap_ring_sparse_mask_matches_sequential():
  """Regression (review finding): ONE masked token per micro-batch —
  fewer than the seq-shard count.  The emit's div0 clamp must see the
  PSUM'd total mask count, not a pmean'd fraction that silently engages
  the clamp and shrinks loss and grads."""
  from easyparallellibrary_tpu.models.bert import make_bert_smap_grad_fn

  env = epl.init(epl.Config({"sequence.ring_impl": "dense"}))
  mesh = env.cluster.build_mesh(stage=2, seq=2)
  base = dict(vocab_size=64, num_layers=4, num_heads=4, d_model=32,
              d_ff=64, max_seq_len=16, dtype=jnp.float32,
              seq_parallel=True, attn_impl="ring",
              pipeline_stages=2, num_micro_batch=2)
  pp = Bert(BertConfig(**base))
  r = np.random.RandomState(0)
  B, S = 8, 16
  mask = np.zeros((B, S), np.float32)
  for mb in range(2):           # one masked token per micro-batch
    mask[mb * 4, r.randint(S)] = 1.0
  batch = {"ids": jnp.asarray(r.randint(0, 64, (B, S)), jnp.int32),
           "labels": jnp.asarray(r.randint(0, 64, (B, S)), jnp.int32),
           "mask": jnp.asarray(mask)}
  params = pp.init(jax.random.PRNGKey(0), batch["ids"])["params"]
  seq = Bert(BertConfig(**base, pipeline_debug_sequential=True))

  g_smap = make_bert_smap_grad_fn(pp, mesh)
  (l1, _), g1 = jax.jit(lambda p: g_smap(p, batch, None))(params)
  l2, g2 = jax.jit(jax.value_and_grad(
      lambda p: bert_mlm_loss(seq, p, batch)[0]))(params)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=5e-3, atol=1e-5),
      g1, g2)
