"""Collective wrapper + fusion tests (reference analog:
tests/communicator_test.py)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.communicators import (
    all_gather, all_reduce, all_to_all, batch_all_reduce, broadcast,
    build_fusion_plan, reduce, reduce_scatter, ring_shift,
)

shard_map = jax.shard_map if hasattr(jax, "shard_map") else None
if shard_map is None:  # pragma: no cover
  from jax.experimental.shard_map import shard_map


def _mesh1d(axis="data"):
  env = epl.init()
  return env.cluster.build_mesh()


def _smap(fn, mesh, in_specs, out_specs):
  return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def test_all_reduce_sum():
  mesh = _mesh1d()
  x = jnp.arange(8.0)

  f = _smap(lambda v: all_reduce(v, "data"), mesh, P("data"), P("data"))
  out = f(x)
  np.testing.assert_allclose(out, jnp.full((8,), x.sum()))


def test_all_reduce_ops():
  mesh = _mesh1d()
  x = jnp.arange(8.0) + 1

  for op, expect in [("max", 8.0), ("min", 1.0), ("mean", 4.5)]:
    f = _smap(lambda v, op=op: all_reduce(v, "data", op=op),
              mesh, P("data"), P("data"))
    np.testing.assert_allclose(f(x), jnp.full((8,), expect))
  f = _smap(lambda v: all_reduce(v, "data", op="prod"),
            mesh, P("data"), P("data"))
  np.testing.assert_allclose(f(x), jnp.full((8,), float(np.prod(x))))


def test_all_gather_and_reduce_scatter_roundtrip():
  mesh = _mesh1d()
  x = jnp.arange(16.0)

  def body(v):
    gathered = all_gather(v, "data")          # full vector on each shard
    return reduce_scatter(gathered, "data")   # shard = 8 * own piece

  f = _smap(body, mesh, P("data"), P("data"))
  np.testing.assert_allclose(f(x), 8 * x)


def test_broadcast_from_root():
  mesh = _mesh1d()
  x = jnp.arange(8.0)

  f = _smap(lambda v: broadcast(v, "data", root=3), mesh, P("data"),
            P("data"))
  np.testing.assert_allclose(f(x), jnp.full((8,), 3.0))


def test_reduce_to_root():
  mesh = _mesh1d()
  x = jnp.ones((8,))
  f = _smap(lambda v: reduce(v, "data", root=2), mesh, P("data"), P("data"))
  out = f(x)
  np.testing.assert_allclose(out[2], 8.0)
  assert float(jnp.sum(out)) == 8.0


def test_ring_shift():
  mesh = _mesh1d()
  x = jnp.arange(8.0)
  f = _smap(lambda v: ring_shift(v, "data", 1), mesh, P("data"), P("data"))
  np.testing.assert_allclose(f(x), jnp.roll(x, 1))


def test_all_to_all_reshards_rows_to_cols():
  mesh = _mesh1d()
  # Row-sharded [8,8] -> column-sharded [8,8]: the global data is unchanged
  # but each rank now holds a column instead of a row.
  x = jnp.arange(64.0).reshape(8, 8)

  def body(v):  # v: [1, 8] per rank -> [8, 1] per rank
    return all_to_all(v, "data", split_axis=1, concat_axis=0)

  f = _smap(body, mesh, P("data", None), P(None, "data"))
  np.testing.assert_allclose(f(x), x)


def test_fusion_plan_roundtrip():
  tree = {
      "a": jnp.arange(5.0),
      "b": jnp.ones((3, 4), jnp.float32),
      "c": jnp.arange(6, dtype=jnp.int32).reshape(2, 3),
  }
  plan = build_fusion_plan(tree, fusion_threshold_mb=1)
  buffers = plan.flatten(tree)
  # int32 and float32 leaves must land in different buckets.
  assert plan.num_buckets == 2
  out = plan.unflatten(buffers)
  jax.tree_util.tree_map(np.testing.assert_allclose, out, tree)


def test_fusion_bucket_size_split():
  # 3 leaves of 1 MB with a 2 MB threshold -> 2 buckets.
  mb = 1024 * 1024 // 4
  tree = [jnp.zeros((mb,)), jnp.zeros((mb,)), jnp.zeros((mb,))]
  plan = build_fusion_plan(tree, fusion_threshold_mb=2)
  assert plan.num_buckets == 2


def test_fusion_max_splits_cap():
  tree = [jnp.zeros((1024 * 1024 // 4,)) for _ in range(8)]
  plan = build_fusion_plan(tree, fusion_threshold_mb=1, max_splits=3)
  assert plan.num_buckets <= 3


def test_batch_all_reduce_matches_per_leaf():
  mesh = _mesh1d()
  tree = {
      "w": jnp.arange(16.0).reshape(8, 2),
      "b": jnp.arange(8.0),
  }

  def fused(t):
    return batch_all_reduce(t, "data")

  def perleaf(t):
    return jax.tree_util.tree_map(lambda v: all_reduce(v, "data"), t)

  spec = {"w": P("data", None), "b": P("data")}
  f1 = _smap(fused, mesh, (spec,), spec)
  f2 = _smap(perleaf, mesh, (spec,), spec)
  jax.tree_util.tree_map(np.testing.assert_allclose, f1(tree), f2(tree))


def test_batch_all_reduce_compressed():
  mesh = _mesh1d()
  tree = {"w": jnp.ones((8, 4)) * 0.5}
  spec = {"w": P("data", None)}
  f = _smap(functools.partial(batch_all_reduce, axis_name="data",
                              compress_dtype="bf16", compress_scale=1.0),
            mesh, (spec,), spec)
  np.testing.assert_allclose(f(tree)["w"], jnp.full((8, 4), 4.0), rtol=1e-2)


def test_fusion_zero_element_leaf():
  # A shape-(0,) leaf must not corrupt bucket offsets.
  tree = {"a": jnp.zeros((0,)), "b": jnp.arange(4.0), "c": jnp.ones(())}
  plan = build_fusion_plan(tree)
  out = plan.unflatten(plan.flatten(tree))
  jax.tree_util.tree_map(np.testing.assert_allclose, out, tree)


def test_fusion_cap_converges_exactly():
  mb = 1024 * 1024 // 4
  tree = [jnp.zeros((mb,)) for _ in range(8)]
  plan = build_fusion_plan(tree, fusion_threshold_mb=1, max_splits=7)
  assert plan.num_buckets == 7


def test_batch_all_reduce_communicator_pool_bound():
  mesh = _mesh1d()
  mb = 1024 * 256 // 4
  tree = [jnp.ones((mb,)) for _ in range(6)]
  spec = [P("data")] * 6
  f = _smap(functools.partial(batch_all_reduce, axis_name="data",
                              fusion_threshold_mb=1, num_communicators=2),
            mesh, (spec,), spec)
  out = f(tree)
  for leaf in out:
    np.testing.assert_allclose(leaf, jnp.full((mb,), 8.0))


def test_communicator_pool_serialization_in_lowered_hlo():
  """num_communicators=n is not just accepted — it materializes as an
  optimization-barrier chain in the lowered program (bucket i's input
  tied to bucket i-n's result), the structural analog of the reference
  pool's per-communicator serial control deps
  (epl/communicators/communication_pool.py:92-104)."""
  mesh = _mesh1d()
  # The plan is built inside shard_map on LOCAL shards: 1 MB per shard
  # per leaf (8 MB global) with a 1 MB threshold -> one bucket per leaf.
  elems = 8 * 1024 * 1024 // 4
  tree = [jnp.ones((elems,)) for _ in range(6)]
  spec = [P("data")] * 6

  def lowered_text(n):
    f = _smap(functools.partial(batch_all_reduce, axis_name="data",
                                fusion_threshold_mb=1,
                                num_communicators=n),
              mesh, (spec,), spec)
    return jax.jit(f).lower(tree).as_text()

  free = lowered_text(0)
  serial = lowered_text(1)
  pooled = lowered_text(2)
  barrier = "stablehlo.optimization_barrier"
  op = 'stablehlo.all_reduce"'
  assert free.count(op) == serial.count(op) == pooled.count(op) == 6
  assert free.count(barrier) == 0
  # Pool of 1 fully serializes: buckets 1..5 each wait on i-1; pool of
  # 2 leaves two in flight: buckets 2..5 wait on i-2.  The knob changes
  # the lowered schedule monotonically, not just the python plan.
  assert serial.count(barrier) == 5
  assert pooled.count(barrier) == 4
