"""Device-truth observability (ISSUE 14): compiled-twin cost cards,
per-site measured collective bytes feeding the overlap planner, HBM
watermark gauges, and the perf regression gate.

Acceptance contract:

* QUICK — device observability fully enabled (introspector + HBM
  gauges + cost-card collection) on a fault-free speculative serving
  episode is BIT-IDENTICAL to the baseline stream, with the fused-step
  compile count still 1 (the AOT capture must not touch the jit call
  cache) and zero added host syncs (the whole episode runs under
  ``jax.transfer_guard_device_to_host("disallow")``).
* ``plan_collective_matmul`` (through ``resolve_num_chunks(site=...)``)
  flips its chunking decision when fed an introspector-measured
  per-site byte count that disagrees with the analytic model, and falls
  back BIT-IDENTICALLY when no measurement exists.
* ``make perf-gate`` passes on the shipped tree (checked-in
  ``perf_budget.json`` vs freshly collected cards + the shipped
  BENCH_EVIDENCE.json) and demonstrably fails on a seeded regression
  (halved flops budget), and REFUSES malformed evidence records.
"""

import copy
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.communicators.overlap import (
    resolve_num_chunks)
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.observability import device as device_lib
from easyparallellibrary_tpu.observability import perfgate
from easyparallellibrary_tpu.observability import slo as slo_lib
from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.observability.device import (
    DeviceIntrospector, specs_of)
from easyparallellibrary_tpu.observability.registry import (
    DEVICE_NAMESPACE, MetricRegistry)
from easyparallellibrary_tpu.parallel.planner import (
    SITE_GATHER_MATMUL, SITE_ROW_DENSE, plan_collective_matmul)
from easyparallellibrary_tpu.serving import (
    ContinuousBatchingEngine, DraftModelDrafter, Request)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = GPTConfig(vocab_size=64, num_layers=1, num_heads=4, d_model=32,
                 d_ff=64, max_seq_len=32, dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _drop_ambient_observability():
  yield
  trace_lib.reset()
  slo_lib.reset()
  device_lib.reset()


def _tiny_model():
  model = GPT(TINY)
  params = model.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 4), jnp.int32))["params"]
  return model, params


def _prompts(n=4, seed=1):
  r = np.random.RandomState(seed)
  return [r.randint(0, 64, (m,)).astype(np.int32)
          for m in (5, 3, 6, 2)[:n]]


def _drive(eng, prompts):
  """A staggered speculative episode: two joins mid-flight."""
  out = {}
  for i in (0, 1):
    eng.submit(Request(uid=f"r{i}", prompt=prompts[i],
                       max_new_tokens=5 + i))
  for _ in range(2):
    for fin in eng.step():
      out[fin.uid] = fin.tokens
  for i in (2, 3):
    eng.submit(Request(uid=f"r{i}", prompt=prompts[i],
                       max_new_tokens=5 + i))
  out.update(eng.run())
  return out


# ------------------------------------------------- quick: fault-free


@pytest.mark.quick
def test_device_observability_fault_free_bit_identical():
  """The quick-matrix guard: introspector + HBM gauges + cost-card
  collection fully enabled on a fault-free speculative serving episode
  changes NOTHING — bit-identical streams, fused-step cache size 1,
  sentinel silent, and the whole episode (captures included) legal
  under a device-to-host transfer guard."""
  prompts = _prompts()
  model, params = _tiny_model()

  epl.init()
  base_eng = ContinuousBatchingEngine(
      model, params, num_slots=2, prefill_chunk=4,
      drafter=DraftModelDrafter(model, params, k=2))
  baseline = _drive(base_eng, prompts)
  base_eng.close()
  assert device_lib.get_introspector() is None

  config = epl.Config({"observability": {"device": {"enabled": True}}})
  epl.init(config)
  registry = MetricRegistry()
  eng = ContinuousBatchingEngine(
      model, params, num_slots=2, prefill_chunk=4,
      drafter=DraftModelDrafter(model, params, k=2), registry=registry)
  with jax.transfer_guard_device_to_host("disallow"):
    observed = _drive(eng, prompts)

  # Bit-identical streams.
  assert sorted(observed) == sorted(baseline)
  for uid in baseline:
    np.testing.assert_array_equal(observed[uid], baseline[uid],
                                  err_msg=f"req {uid}")
  # Compile-once held THROUGH the AOT capture (the introspector lowers
  # and compiles the same twin, but never through the call cache).
  assert eng._step_fn._cache_size() == 1
  assert eng._compile_sentinel.recompiles == 0
  # The cards exist: fused step (speculative twin), sanitize-less
  # (resilience off), and the drafter's rollout.
  intro = device_lib.get_introspector()
  assert intro is not None
  card = intro.card("serving/fused_step")
  assert card is not None and card.flops > 0
  assert card.compile_count == 1
  assert card.donation_requested and card.donation_verified
  assert card.meta["tokens_per_step"] == 2 * 4
  drafter_card = intro.card("serving/drafter")
  assert drafter_card is not None and drafter_card.flops > 0
  # HBM gauges published under the device namespace (CPU: the static
  # cost-card bound, explicitly tagged as such).
  latest = registry.latest()
  key = f"{DEVICE_NAMESPACE}/hbm_peak_bytes"
  assert latest[key] > 0
  gauges = intro.hbm_gauges()
  assert gauges["hbm_source"] in ("memory_stats", "cost_card")
  # The gauges/cards ride diagnostic bundles via the engine's context.
  ctx = eng._capture_context()
  assert "serving/fused_step" in ctx["device"]["cost_cards"]
  eng.close()


# -------------------------------- site feed: the measured flip (pin)


def test_resolve_num_chunks_flips_on_measured_site_bytes():
  """THE acceptance pin: the crossover flips in BOTH directions when an
  introspector measurement disagrees with the analytic model, and is
  bit-identical to the analytic decision when no measurement exists."""
  config = epl.Config()
  kw = dict(config=config, dtype=jnp.bfloat16)

  # Analytic says FUSED for a compute-heavy site whose MODELED wire
  # traffic is negligible (a scatter of [m/n, n_out] blocks after a
  # deep-contraction matmul: nothing worth hiding, per the model)...
  deep = dict(m=8, k=2 ** 20, n_out=512)
  analytic = plan_collective_matmul("matmul_reduce_scatter",
                                    axis_size=8, dtype_bytes=2, **deep)
  assert not analytic.enabled
  assert resolve_num_chunks("matmul_reduce_scatter", 8,
                            site=SITE_GATHER_MATMUL, **deep, **kw) == 1
  # ...until a MEASURED wire-byte count (this site's real collective
  # traffic, comparable to its MXU time) says overlap pays after all.
  intro = device_lib.install(DeviceIntrospector())
  intro.record_site_bytes(SITE_GATHER_MATMUL, 4e6)
  flipped = resolve_num_chunks("matmul_reduce_scatter", 8,
                               site=SITE_GATHER_MATMUL, **deep, **kw)
  assert flipped >= 2, "measured bytes did not flip the crossover ON"

  # Analytic says OVERLAP for a big site...
  big = dict(m=8192, k=8192, n_out=8192)
  analytic = plan_collective_matmul("all_gather_matmul", axis_size=8,
                                    dtype_bytes=2, **big)
  assert analytic.enabled and analytic.num_chunks >= 2
  assert resolve_num_chunks("all_gather_matmul", 8,
                            site=SITE_ROW_DENSE, **big, **kw) >= 2
  # ...until a measurement reveals almost no wire traffic.
  intro.record_site_bytes(SITE_ROW_DENSE, 1.0)
  assert resolve_num_chunks("all_gather_matmul", 8,
                            site=SITE_ROW_DENSE, **big, **kw) == 1

  # Fallback bit-identity: an installed introspector with NO
  # measurement for a site decides exactly like no introspector at all.
  device_lib.install(DeviceIntrospector())
  for dims in (deep, big, dict(m=256, k=512, n_out=128)):
    with_feed = resolve_num_chunks("all_gather_matmul", 8,
                                   site="unmeasured/site", **dims, **kw)
    device_lib.reset()
    bare = resolve_num_chunks("all_gather_matmul", 8,
                              site="unmeasured/site", **dims, **kw)
    assert with_feed == bare
    device_lib.install(DeviceIntrospector())


def test_site_registration_and_attribution():
  """resolve_num_chunks REGISTERS the site's analytic signature; a
  captured program whose fused collective matches it feeds the
  measurement store (result bytes -> ring wire bytes); a non-matching
  program leaves the site unmeasured (analytic fallback, no guessing)."""
  intro = device_lib.install(DeviceIntrospector())
  config = epl.Config()
  resolve_num_chunks("matmul_reduce_scatter", 4, m=16, k=8, n_out=8,
                     dtype=jnp.float32, config=config,
                     site=SITE_ROW_DENSE)
  info = intro.sites()[SITE_ROW_DENSE]
  assert info.kind == "matmul_reduce_scatter" and info.axis_n == 4
  # Expected fused result: [m/n, n_out] f32 = 4*8*4 = 128 bytes.
  assert info.expected_result_bytes() == 128.0
  matched = intro._attribute_sites([("reduce_scatter", 128.0),
                                    ("all_gather", 4096.0)])
  assert matched == {SITE_ROW_DENSE: 128.0 * 3}      # (n-1) ring copies
  assert intro.measured_site_bytes(SITE_ROW_DENSE) == 384.0
  # Way-off sizes never match (factor bound): the store is untouched.
  intro2 = device_lib.install(DeviceIntrospector())
  resolve_num_chunks("matmul_reduce_scatter", 4, m=16, k=8, n_out=8,
                     dtype=jnp.float32, config=config,
                     site=SITE_ROW_DENSE)
  assert intro2._attribute_sites([("reduce_scatter", 5000.0)]) == {}
  assert intro2.measured_site_bytes(SITE_ROW_DENSE) is None


def test_capture_twin_attributes_real_lowered_collective():
  """End to end through a REAL lowered program: a jitted shard_map
  psum_scatter's StableHLO reduce_scatter op is attributed back to the
  registered site, and the wire-byte figure lands in the store the
  overlap policy reads."""
  from jax.experimental.shard_map import shard_map
  from jax.sharding import Mesh, PartitionSpec as P
  intro = device_lib.install(DeviceIntrospector())
  # Site expecting a [4, 8] f32 fused reduce_scatter result (128 B).
  intro.register_site("test/rs_site", kind="reduce_scatter", axis_n=4,
                      m=16, k=8, n_out=0, dtype_bytes=4)
  mesh = Mesh(np.array(jax.devices()[:1]), ("x",))
  fn = jax.jit(shard_map(
      lambda v: jax.lax.psum_scatter(v, "x", scatter_dimension=0,
                                     tiled=True),
      mesh=mesh, in_specs=P("x"), out_specs=P("x")))
  card = intro.capture_twin(
      "test/rs_twin", fn,
      (jax.ShapeDtypeStruct((4, 8), jnp.float32),))
  assert card is not None and card.collective_ops == 1
  assert card.site_bytes == {"test/rs_site": 128.0 * 3}
  assert intro.measured_site_bytes("test/rs_site") == 384.0


# ---------------------------------------------------- introspector units


def test_capture_is_idempotent_and_failure_degrades():
  intro = DeviceIntrospector()
  fn = jax.jit(lambda x: x * 2)
  spec = (jax.ShapeDtypeStruct((4,), jnp.float32),)
  card1 = intro.capture_twin("t", fn, spec)
  card2 = intro.capture_twin("t", fn, spec)
  assert card1 is card2 and intro.captures == 1
  # A twin without the AOT surface (a plain function, a chaos wrapper)
  # degrades to a logged skip — never an exception.
  assert intro.capture_twin("broken", lambda x: x, spec) is None
  assert intro.capture_failures == 1
  assert not intro.has_card("broken")


def test_donation_verification_flag():
  spec = (jax.ShapeDtypeStruct((8, 8), jnp.float32),)
  intro = DeviceIntrospector()
  donated = intro.capture_twin(
      "donated", jax.jit(lambda x: x + 1, donate_argnums=0), spec)
  plain = intro.capture_twin("plain", jax.jit(lambda x: x + 1), spec)
  assert donated.donation_requested and donated.donation_verified
  assert donated.alias_bytes > 0 or donated.donation_verified
  assert not plain.donation_requested and not plain.donation_verified


def test_hbm_gauges_cost_card_fallback_and_publish():
  intro = DeviceIntrospector()
  # CPU: memory_stats() is None, no cards yet -> no gauges at all.
  if jax.local_devices()[0].memory_stats() is None:
    assert intro.hbm_gauges() == {}
  intro.capture_twin("t", jax.jit(lambda x: x @ x),
                     (jax.ShapeDtypeStruct((16, 16), jnp.float32),))
  gauges = intro.hbm_gauges()
  assert gauges["hbm_peak_bytes"] > 0
  if gauges["hbm_source"] == "cost_card":
    assert "hbm_frac" not in gauges  # a bound over no limit is no frac
  registry = MetricRegistry()
  intro.publish_hbm(7, registry=registry)
  assert f"{DEVICE_NAMESPACE}/hbm_peak_bytes" in registry.latest()
  # Monitor path (registry-less engines): the record reaches observe.
  seen = []

  class _Mon:
    def observe(self, step, record):
      seen.append((step, dict(record)))

  intro.publish_hbm(8, monitor=_Mon())
  assert seen and f"{DEVICE_NAMESPACE}/hbm_peak_bytes" in seen[0][1]


def test_hbm_frac_rule_from_config():
  rules = slo_lib.rules_from_config(
      epl.Config({"observability": {"slo": {"hbm_frac": 0.9}}})
      .observability.slo)
  hbm = [r for r in rules if r.name == "hbm_high"]
  assert len(hbm) == 1 and hbm[0].metric == "hbm_frac"
  assert hbm[0].target == 0.9
  with pytest.raises(ValueError, match="hbm_frac"):
    epl.Config({"observability": {"slo": {"hbm_frac": 1.5}}})


def test_ensure_configured_contract():
  # Off by default: no ambient introspector.
  epl.init()
  assert device_lib.ensure_configured() is None
  # Enabled via the ambient config: auto-built, stable across calls.
  config = epl.Config({"observability": {"device": {"enabled": True}}})
  epl.init(config)
  intro = device_lib.ensure_configured()
  assert intro is not None
  assert device_lib.ensure_configured() is intro
  # Explicit install wins over config.
  mine = DeviceIntrospector()
  device_lib.install(mine)
  assert device_lib.ensure_configured() is mine
  device_lib.reset()
  # Ambient off-config tears the auto instance down.
  epl.init()
  assert device_lib.ensure_configured() is None


def test_specs_of_passthrough():
  tree = {"a": jnp.ones((2, 3)), "b": 7, "c": np.zeros((4,), np.int32)}
  spec = specs_of(tree)
  assert isinstance(spec["a"], jax.ShapeDtypeStruct)
  assert spec["a"].shape == (2, 3)
  assert spec["b"] == 7
  assert spec["c"].shape == (4,)


def test_fit_step_cost_card_captured(tmp_path):
  """fit() captures the train step's cost card at the first dispatch
  (train/fit_step) with device observability enabled, donation
  verified (parallelize donates the state), and the fit-step compile
  count stays 1 through the capture."""
  import optax
  from flax import linen as nn

  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state, make_train_step,
      parallelize)
  from easyparallellibrary_tpu.runtime.loop import fit

  epl.init(epl.Config({"observability": {"device": {"enabled": True}}}))

  class Net(nn.Module):
    @nn.compact
    def __call__(self, x):
      return nn.Dense(1)(jnp.tanh(nn.Dense(8)(x)))

  mesh = epl.current_plan().build_mesh()
  model = Net()
  r = np.random.RandomState(0)
  batch = {"x": jnp.asarray(r.randn(16, 4), jnp.float32),
           "y": jnp.asarray(r.randn(16, 1), jnp.float32)}

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, batch["x"])["params"],
                             tx=optax.adam(1e-2))

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))

  def loss_fn(params, b, rng):
    pred = model.apply({"params": params}, b["x"])
    return jnp.mean((pred - b["y"]) ** 2), {}

  step = parallelize(make_train_step(loss_fn), mesh, shardings)
  fit(step, state, [batch], num_steps=3,
      checkpoint_dir=str(tmp_path / "ck"), log_every=2,
      shardings=shardings)
  assert step.jitted._cache_size() == 1
  intro = device_lib.get_introspector()
  card = intro.card("train/fit_step")
  assert card is not None and card.flops > 0
  assert card.donation_requested and card.donation_verified


# ----------------------------------------------------------- perf gate


@pytest.fixture(scope="module")
def collected_cards():
  """One card collection for every gate test (each engine build
  compiles, so the cost is paid once per module)."""
  epl.init()
  try:
    return perfgate.collect_cards()
  finally:
    trace_lib.reset()
    slo_lib.reset()
    device_lib.reset()


def test_perf_gate_passes_on_shipped_tree(collected_cards):
  """`make perf-gate` on the shipped tree: the checked-in budget holds
  against freshly collected cards AND the shipped evidence ledger."""
  budget = perfgate.load_budget()
  assert budget.get("cost_cards"), "shipped budget pins no twins"
  violations = perfgate.check_cost_cards(budget, collected_cards)
  assert violations == []
  violations = perfgate.check_bench(
      budget, os.path.join(REPO, "BENCH_EVIDENCE.json"))
  assert violations == []


def test_perf_gate_fails_on_seeded_regression(collected_cards, tmp_path):
  """Seed a regression: halve the flops budget (equivalently, double
  the measured flops) — the gate must fail with an attributed
  violation; same for a compile-count bust and a lost donation."""
  budget = copy.deepcopy(perfgate.load_budget())
  pins = budget["cost_cards"]["serving/fused_step"]
  pins["flops"]["max"] /= 2.0
  violations = perfgate.check_cost_cards(budget, collected_cards)
  assert any("serving/fused_step].flops" in v and "exceeds" in v
             for v in violations)
  # End to end through run_gate with the tampered budget on disk.
  tampered = tmp_path / "perf_budget.json"
  tampered.write_text(json.dumps(budget))
  errs = perfgate.run_gate(str(tampered),
                           os.path.join(REPO, "BENCH_EVIDENCE.json"),
                           cards=collected_cards)
  assert errs, "tampered budget passed the gate"
  # A recompile shows up as compile_count 2 and busts its exact pin.
  worse = {**collected_cards,
           "serving/fused_step": {**collected_cards["serving/fused_step"],
                                  "compile_count": 2.0,
                                  "donation_verified": 0.0}}
  violations = perfgate.check_cost_cards(perfgate.load_budget(), worse)
  assert any("compile_count" in v for v in violations)
  assert any("donation_verified" in v and "below" in v
             for v in violations)
  # A budgeted twin that was never captured is itself a violation.
  missing = {k: v for k, v in collected_cards.items()
             if k != "serving/fused_step"}
  violations = perfgate.check_cost_cards(perfgate.load_budget(), missing)
  assert any("not captured" in v for v in violations)


def test_perf_gate_refuses_malformed_evidence(tmp_path):
  """Malformed ledger records are REFUSED (violations), never silently
  skipped; a budget pin whose record/path is absent also fails."""
  evidence = tmp_path / "ev.json"
  evidence.write_text(json.dumps({"records": [
      {"metric": "good", "value": 1.0, "unix_time": 5.0},
      {"metric": "", "unix_time": "not-a-number"},          # malformed
  ]}))
  budget = {"version": 1, "cost_cards": {},
            "bench": [{"metric": "good", "path": "value",
                       "op": ">=", "target": 1},
                      {"metric": "absent", "path": "value",
                       "op": ">=", "target": 0}]}
  errs = perfgate.check_bench(budget, str(evidence))
  assert any("malformed" in e for e in errs)
  assert any("no evidence record named 'absent'" in e for e in errs)
  # The structural pin itself enforces: regress the value -> violation.
  evidence.write_text(json.dumps({"records": [
      {"metric": "good", "value": 0.5, "unix_time": 6.0}]}))
  budget["bench"] = [{"metric": "good", "path": "value",
                     "op": ">=", "target": 1}]
  errs = perfgate.check_bench(budget, str(evidence))
  assert len(errs) == 1 and "violates" in errs[0]


def test_validated_evidence_writer_rejects_malformed(tmp_path):
  """benchmarks/_evidence.py (the shared writer): schema errors raise
  at WRITE time, valid records land with timestamps filled."""
  import importlib.util
  spec = importlib.util.spec_from_file_location(
      "_evidence", os.path.join(REPO, "benchmarks", "_evidence.py"))
  _evidence = importlib.util.module_from_spec(spec)
  spec.loader.exec_module(_evidence)
  path = str(tmp_path / "ev.json")
  written = _evidence.append_record(
      {"metric": "m", "config": {"a": 1}, "tokens_per_s": 9.0},
      path=path)
  assert written["unix_time"] > 0
  assert _evidence.latest_record("m", path=path)["tokens_per_s"] == 9.0
  with pytest.raises(ValueError, match="malformed"):
    _evidence.append_record({"config": {}}, path=path)      # no name
  with pytest.raises(ValueError, match="payload"):
    _evidence.append_record({"metric": "empty"}, path=path)  # no metrics
