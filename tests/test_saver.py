"""Checkpoint save/restore tests (reference analog: tests/saver_test.py +
ShardingLoader coverage)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn
from jax.sharding import PartitionSpec as P

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu import ops
from easyparallellibrary_tpu.parallel import (
    TrainState, create_sharded_train_state, named_sharding)
from easyparallellibrary_tpu.runtime.saver import (
    latest_step, restore_checkpoint, save_checkpoint)


class Net(nn.Module):
  tp: bool = False

  @nn.compact
  def __call__(self, x):
    if self.tp:
      with epl.split():
        return ops.Dense(64)(x)
    return ops.Dense(64, parallel="none")(x)


def _state(tp=False):
  env = epl.init()
  if tp:
    with epl.split():
      pass
  mesh = epl.current_plan().build_mesh()
  model = Net(tp=tp)
  x = jnp.ones((8, 16))

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, x)["params"],
                             tx=optax.adam(1e-3))

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  return mesh, state, shardings


def test_roundtrip(tmp_path):
  mesh, state, shardings = _state()
  path = save_checkpoint(str(tmp_path / "ckpt"), state.params, step=7)
  restored, step = restore_checkpoint(path, target=state.params)
  assert step == 7
  assert latest_step(path) == 7
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b),
      nn.unbox(state.params), nn.unbox(restored))


def test_small_shard_buckets(tmp_path):
  mesh, state, shardings = _state()
  # Force tiny buckets: every leaf gets its own shard file.
  path = save_checkpoint(str(tmp_path / "ckpt"), state.params, step=1,
                         shard_mb=1)
  files = [f for f in os.listdir(path) if f.endswith(".npz")]
  assert len(files) >= 1
  restored, _ = restore_checkpoint(path, target=state.params)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b),
      nn.unbox(state.params), nn.unbox(restored))


def test_restore_with_resharding_to_tp_mesh(tmp_path):
  # Save from a replicated (pure DP) layout...
  mesh, state, shardings = _state(tp=False)
  path = save_checkpoint(str(tmp_path / "ckpt"), state.params)
  # ...restore onto a tensor-parallel mesh with model-axis sharding.
  mesh2, state2, shardings2 = _state(tp=True)
  restored, _ = restore_checkpoint(
      path, target=state2.params, shardings=shardings2.params)
  k = nn.unbox(restored)["Dense_0"]["kernel"]
  assert k.sharding.shard_shape(k.shape)[1] == k.shape[1] // 8
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
      nn.unbox(state.params), nn.unbox(restored))


def test_assign_map_rename(tmp_path):
  mesh, state, shardings = _state()
  path = save_checkpoint(str(tmp_path / "ckpt"), state.params)
  # Target paths have a different module name; map them back.
  renamed = {"renamed": nn.unbox(state.params)["Dense_0"]}
  restored, _ = restore_checkpoint(
      path, target=renamed, assign_map={r"^renamed/": "Dense_0/"})
  np.testing.assert_allclose(nn.unbox(restored)["renamed"]["kernel"],
                             nn.unbox(state.params)["Dense_0"]["kernel"])


def test_slice_at_load(tmp_path):
  mesh, state, shardings = _state()
  path = save_checkpoint(str(tmp_path / "ckpt"), state.params)
  full = nn.unbox(state.params)["Dense_0"]["kernel"]  # [16, 64]
  target = {"Dense_0": {"kernel": jnp.zeros((8, 32)),
                        "bias": jnp.zeros((64,))}}
  restored, _ = restore_checkpoint(
      path, target=target,
      slice_offsets={"Dense_0/kernel": (4, 16)})
  np.testing.assert_allclose(
      restored["Dense_0"]["kernel"], np.asarray(full)[4:12, 16:48])


def test_missing_tensor_error(tmp_path):
  mesh, state, shardings = _state()
  path = save_checkpoint(str(tmp_path / "ckpt"), state.params)
  with pytest.raises(KeyError):
    restore_checkpoint(path, target={"nope": jnp.zeros((1,))})


def test_orbax_roundtrip(tmp_path):
  from easyparallellibrary_tpu.runtime.saver import (
      restore_checkpoint_orbax, save_checkpoint_orbax)
  mesh, state, shardings = _state()
  path = save_checkpoint_orbax(str(tmp_path / "ock"), state.params, step=3)
  restored = restore_checkpoint_orbax(str(tmp_path / "ock"), 3,
                                      target=state.params)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
      nn.unbox(state.params), restored)
