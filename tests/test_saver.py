"""Checkpoint save/restore tests (reference analog: tests/saver_test.py +
ShardingLoader coverage)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn
from jax.sharding import PartitionSpec as P

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu import ops
from easyparallellibrary_tpu.parallel import (
    TrainState, create_sharded_train_state, named_sharding)
from easyparallellibrary_tpu.runtime.saver import (
    latest_step, restore_checkpoint, save_checkpoint)


class Net(nn.Module):
  tp: bool = False

  @nn.compact
  def __call__(self, x):
    if self.tp:
      with epl.split():
        return ops.Dense(64)(x)
    return ops.Dense(64, parallel="none")(x)


def _state(tp=False):
  env = epl.init()
  if tp:
    with epl.split():
      pass
  mesh = epl.current_plan().build_mesh()
  model = Net(tp=tp)
  x = jnp.ones((8, 16))

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, x)["params"],
                             tx=optax.adam(1e-3))

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  return mesh, state, shardings


def test_roundtrip(tmp_path):
  mesh, state, shardings = _state()
  path = save_checkpoint(str(tmp_path / "ckpt"), state.params, step=7)
  restored, step = restore_checkpoint(path, target=state.params)
  assert step == 7
  assert latest_step(path) == 7
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b),
      nn.unbox(state.params), nn.unbox(restored))


def test_small_shard_buckets(tmp_path):
  mesh, state, shardings = _state()
  # Force tiny buckets: every leaf gets its own shard file.
  path = save_checkpoint(str(tmp_path / "ckpt"), state.params, step=1,
                         shard_mb=1)
  files = [f for f in os.listdir(path) if f.endswith(".npz")]
  assert len(files) >= 1
  restored, _ = restore_checkpoint(path, target=state.params)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b),
      nn.unbox(state.params), nn.unbox(restored))


def test_restore_with_resharding_to_tp_mesh(tmp_path):
  # Save from a replicated (pure DP) layout...
  mesh, state, shardings = _state(tp=False)
  path = save_checkpoint(str(tmp_path / "ckpt"), state.params)
  # ...restore onto a tensor-parallel mesh with model-axis sharding.
  mesh2, state2, shardings2 = _state(tp=True)
  restored, _ = restore_checkpoint(
      path, target=state2.params, shardings=shardings2.params)
  k = nn.unbox(restored)["Dense_0"]["kernel"]
  assert k.sharding.shard_shape(k.shape)[1] == k.shape[1] // 8
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
      nn.unbox(state.params), nn.unbox(restored))


def test_assign_map_rename(tmp_path):
  mesh, state, shardings = _state()
  path = save_checkpoint(str(tmp_path / "ckpt"), state.params)
  # Target paths have a different module name; map them back.
  renamed = {"renamed": nn.unbox(state.params)["Dense_0"]}
  restored, _ = restore_checkpoint(
      path, target=renamed, assign_map={r"^renamed/": "Dense_0/"})
  np.testing.assert_allclose(nn.unbox(restored)["renamed"]["kernel"],
                             nn.unbox(state.params)["Dense_0"]["kernel"])


def test_slice_at_load(tmp_path):
  mesh, state, shardings = _state()
  path = save_checkpoint(str(tmp_path / "ckpt"), state.params)
  full = nn.unbox(state.params)["Dense_0"]["kernel"]  # [16, 64]
  target = {"Dense_0": {"kernel": jnp.zeros((8, 32)),
                        "bias": jnp.zeros((64,))}}
  restored, _ = restore_checkpoint(
      path, target=target,
      slice_offsets={"Dense_0/kernel": (4, 16)})
  np.testing.assert_allclose(
      restored["Dense_0"]["kernel"], np.asarray(full)[4:12, 16:48])


def test_missing_tensor_error(tmp_path):
  mesh, state, shardings = _state()
  path = save_checkpoint(str(tmp_path / "ckpt"), state.params)
  with pytest.raises(KeyError):
    restore_checkpoint(path, target={"nope": jnp.zeros((1,))})


def test_orbax_roundtrip(tmp_path):
  from easyparallellibrary_tpu.runtime.saver import (
      restore_checkpoint_orbax, save_checkpoint_orbax)
  mesh, state, shardings = _state()
  path = save_checkpoint_orbax(str(tmp_path / "ock"), state.params, step=3)
  restored = restore_checkpoint_orbax(str(tmp_path / "ock"), 3,
                                      target=state.params)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
      nn.unbox(state.params), restored)


class UnevenNet(nn.Module):
  """Dense with an out dim (10) the 8-way model axis cannot tile evenly —
  params are zero-padded to 16 under TP (PaddedPartitioned)."""
  tp: bool = False

  @nn.compact
  def __call__(self, x):
    if self.tp:
      with epl.split():
        return ops.Dense(10)(x)
    return ops.Dense(10, parallel="none")(x)


def _uneven_state(tp):
  env = epl.init()
  if tp:
    with epl.split():
      pass
  mesh = epl.current_plan().build_mesh()
  model = UnevenNet(tp=tp)
  x = jnp.ones((4, 16))

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, x)["params"],
                             tx=optax.adam(1e-3))

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  return mesh, model, x, state, shardings


def test_padded_params_saved_at_logical_shape(tmp_path):
  """VERDICT r2 item 5: checkpoints hold LOGICAL shapes — the saver
  slices attested pad regions off (kernel [16, 16]-padded -> stored
  [16, 10]), and re-pads at load into the same layout."""
  import json as _json
  mesh, model, x, state, shardings = _uneven_state(tp=True)
  k = nn.unbox(state.params)["Dense_0"]["kernel"]
  assert k.shape == (16, 16)  # padded in memory
  path = save_checkpoint(str(tmp_path / "ck"), state.params)
  index = _json.load(open(os.path.join(path, "index.json")))
  assert index["leaves"]["Dense_0/kernel"]["shape"] == [16, 10]
  assert index["leaves"]["Dense_0/bias"]["shape"] == [10]

  restored, _ = restore_checkpoint(path, target=state.params,
                                   shardings=shardings.params)
  rk = np.asarray(nn.unbox(restored)["Dense_0"]["kernel"])
  np.testing.assert_allclose(rk, np.asarray(k))
  assert (rk[:, 10:] == 0).all()


def test_checkpoint_portable_across_tensor_layouts(tmp_path):
  """Save under pure DP (logical [16, 10] kernel), load under 8-way TP
  (padded [16, 16]) and vice versa — the round trip the reference's
  ShardingLoader exists for (epl/runtime/saver.py:46-128), which round 2
  admitted was broken for padded dims (config.py tensor_split note)."""
  mesh_dp, model_dp, x, state_dp, sh_dp = _uneven_state(tp=False)
  y_dp = model_dp.apply({"params": state_dp.params}, x)
  path = save_checkpoint(str(tmp_path / "dp"), state_dp.params)

  # DP checkpoint -> TP layout: stored [16, 10] pads up to [16, 16].
  mesh_tp, model_tp, x, state_tp, sh_tp = _uneven_state(tp=True)
  restored, _ = restore_checkpoint(path, target=state_tp.params,
                                   shardings=sh_tp.params)
  y_tp = model_tp.apply({"params": restored}, x)
  np.testing.assert_allclose(np.asarray(y_tp), np.asarray(y_dp),
                             rtol=1e-5, atol=1e-6)

  # TP checkpoint -> DP layout: stored logical loads straight in.
  path_tp = save_checkpoint(str(tmp_path / "tp"), restored)
  mesh2, model2, x, state2, sh2 = _uneven_state(tp=False)
  back, _ = restore_checkpoint(path_tp, target=state2.params,
                               shardings=sh2.params)
  y_back = model2.apply({"params": back}, x)
  np.testing.assert_allclose(np.asarray(y_back), np.asarray(y_dp),
                             rtol=1e-5, atol=1e-6)


def test_unattested_shape_mismatch_still_raises(tmp_path):
  """Padding is gated on the PaddedPartitioned attestation: restoring a
  too-small tensor into a plain param stays a hard error."""
  small = {"w": jnp.ones((4, 4))}
  path = save_checkpoint(str(tmp_path / "s"), small)
  target = {"w": jnp.zeros((4, 8))}
  with pytest.raises(ValueError, match="out of bounds"):
    restore_checkpoint(path, target=target)


def test_attested_repad_requires_logical_coverage():
  """ADVICE r3: re-padding a PaddedPartitioned target may only fabricate
  the attested pad region — a stored value that does not cover the whole
  logical region must raise, never silently zero-fill real parameters."""
  from easyparallellibrary_tpu.runtime.saver import _slice_to_shape

  # Stored == logical: pads up to the padded target, pad region zero.
  out = _slice_to_shape(np.ones((8, 10)), (16, 10), logical_shape=(8, 10))
  assert out.shape == (16, 10)
  assert (out[8:] == 0).all() and (out[:8] == 1).all()

  # Stored smaller than logical: rows 4..8 are REAL parameters — refuse.
  with pytest.raises(ValueError, match="logical"):
    _slice_to_shape(np.ones((4, 10)), (16, 10), logical_shape=(8, 10))
