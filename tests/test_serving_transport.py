"""Replica transports (ISSUE 12): process-isolated replicas behind a
wire with real timeouts, retries, and SIGKILL-survivable failover.

The acceptance contract (`make chaos-proc`): with two ProcessTransport
replicas — each a spawned subprocess owning its own JAX runtime —
``os.kill(pid, SIGKILL)`` of one mid-decode loses ZERO requests, the
recovered streams are bit-exact vs the fault-free single-engine oracle,
and the survivor's fused-step compile count stays 1 (failover is a
prefix replay — no new shapes).  The InprocTransport default is
byte-for-byte PR-8 behavior (the fault-free N=1 router stream stays
bit-identical to the bare engine with zero added recompiles).  The
ambiguous-timeout cases are pinned: a submit whose reply is dropped
after the child applied it admits exactly once (uid dedup), and a step
reply lost mid-flight never double-commits tokens on recovery replay
(journal watermark resync + deterministic regeneration).

Subprocess spawns cost seconds each (child JAX import + engine
compile); the heavier episodes (SIGSTOP stalls that must burn wire
deadlines, breaker-probe respawns that must burn cooldowns) are
``slow``-marked for the tier-1 window — `make chaos-proc` runs them
all.
"""

import json
import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.profiler.serving import (
    ServingStats, fleet_summary)
from easyparallellibrary_tpu.serving import (
    ContinuousBatchingEngine, InprocTransport, ProcessTransport,
    ReplicaDeadError, Request, Router, TransportTimeout)
from easyparallellibrary_tpu.serving import transport as transport_lib
from easyparallellibrary_tpu.serving.replica import EngineReplica
from easyparallellibrary_tpu.serving.scheduler import SNAPSHOT_VERSION
from easyparallellibrary_tpu.testing import chaos
from easyparallellibrary_tpu.testing.factories import tiny_gpt
from easyparallellibrary_tpu.utils.retry import retry_call

FACTORY = {"fn": "easyparallellibrary_tpu.testing.factories:tiny_gpt"}
GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "request_snapshot_v1.json")
GOLDEN_V2 = os.path.join(os.path.dirname(__file__), "golden",
                         "request_snapshot_v2.json")


def _prompts(n, plen=6, vocab=64, seed=0):
  r = np.random.RandomState(seed)
  return [r.randint(0, vocab, (plen,)).astype(np.int32)
          for _ in range(n)]


def _oracle_outputs(prompts, max_new=10, **engine_kwargs):
  """Fault-free single-engine streams from the SAME factory the child
  processes build from — the bit-exactness baseline."""
  model, params = tiny_gpt()
  eng = ContinuousBatchingEngine(model, params, num_slots=4,
                                 prefill_chunk=4, **engine_kwargs)
  for i, p in enumerate(prompts):
    eng.submit(Request(uid=i, prompt=p, max_new_tokens=max_new))
  out = eng.run()
  eng.close()
  return out


def _process_config(**router):
  conf = {"transport": "process", "rpc_timeout_s": 60.0,
          "rpc_retries": 2, "rpc_backoff_s": 0.05}
  conf.update(router)
  return epl.Config({"serving": {"router": conf}})


def _assert_no_orphans(pids):
  time.sleep(0.1)
  for pid in pids:
    if pid is None:
      continue
    try:
      os.kill(pid, 0)
    except ProcessLookupError:
      continue
    pytest.fail(f"orphan replica child still alive: pid {pid}")


# --------------------------------------------------- snapshot versioning


def test_request_snapshot_matches_v1_golden():
  """The v1 wire shape stays READABLE forever: a v1 snapshot restores
  with every v2 field at its compat default (``checkpoint_version``
  None = unpinned), and re-snapshotting emits the pinned v2 shape —
  a future field change must bump SNAPSHOT_VERSION and grow a new
  golden, not silently reshape what crosses the failover wire."""
  with open(GOLDEN) as f:
    golden = json.load(f)
  restored = Request.restore(golden)
  assert restored.uid == "golden-1"
  assert restored.priority == "latency"
  assert restored.checkpoint_version is None
  assert np.array_equal(restored.prompt, np.asarray([5, 6, 7, 8]))
  with open(GOLDEN_V2) as f:
    golden_v2 = json.load(f)
  resnap = json.loads(json.dumps(restored.snapshot()))
  assert resnap == golden_v2
  assert golden["v"] == 1


def test_request_snapshot_matches_v2_golden():
  """The v2 wire shape is PINNED (v2 added ``checkpoint_version``, the
  blue/green rollout's cross-version replay fence)."""
  with open(GOLDEN_V2) as f:
    golden = json.load(f)
  restored = Request.restore(golden)
  assert restored.uid == "golden-1"
  resnap = json.loads(json.dumps(restored.snapshot()))
  assert resnap == golden
  assert golden["v"] == SNAPSHOT_VERSION == 2


def test_request_snapshot_carries_version_and_rejects_unknown():
  snap = Request(uid="u", prompt=np.asarray([1, 2], np.int32),
                 max_new_tokens=3).snapshot()
  assert snap["v"] == SNAPSHOT_VERSION
  # Pre-versioning snapshots (no "v") read as v1 — same field set.
  legacy = {k: v for k, v in snap.items() if k != "v"}
  assert Request.restore(legacy).uid == "u"
  bad = dict(snap, v=99)
  with pytest.raises(ValueError, match="snapshot version 99"):
    Request.restore(bad)


# --------------------------------------------------------- wire plumbing


def test_frame_reader_survives_partial_frames_and_timeouts():
  import socket
  a, b = socket.socketpair()
  try:
    reader = transport_lib.FrameReader(a)
    payload = json.dumps({"id": 1, "m": "x"}).encode()
    frame = transport_lib._LEN.pack(len(payload)) + payload
    # First half only: the read must time out WITHOUT losing the bytes.
    b.sendall(frame[:3])
    with pytest.raises(TransportTimeout):
      reader.read(0.05)
    b.sendall(frame[3:])
    assert reader.read(0.5) == {"id": 1, "m": "x"}
    # Two frames in one burst: framing separates them.
    b.sendall(frame + frame)
    assert reader.read(0.5)["id"] == 1
    assert reader.read(0.5)["id"] == 1
    b.close()
    with pytest.raises(ReplicaDeadError):
      reader.read(0.5)
  finally:
    a.close()


def test_retry_jitter_bounds(monkeypatch):
  sleeps = []
  monkeypatch.setattr("time.sleep", sleeps.append)
  calls = {"n": 0}

  def flaky():
    calls["n"] += 1
    if calls["n"] <= 2:
      raise TransportTimeout("transient")
    return "ok"

  assert retry_call(flaky, retries=2, backoff_s=0.1, jitter=0.5,
                    exceptions=(TransportTimeout,)) == "ok"
  assert len(sleeps) == 2
  assert 0.1 <= sleeps[0] <= 0.1 * 1.5 + 1e-9
  assert 0.2 <= sleeps[1] <= 0.2 * 1.5 + 1e-9
  with pytest.raises(ValueError, match="jitter"):
    retry_call(lambda: None, retries=0, backoff_s=0.0, jitter=-1.0)


def test_serving_stats_state_roundtrip_feeds_fleet_summary():
  clock = [0.0]
  stats = ServingStats(clock=lambda: clock[0])
  for uid in range(3):
    stats.note_submitted(uid)
    clock[0] += 0.01
    stats.note_first_token(uid)
    clock[0] += 0.05
    stats.note_finished(uid, new_tokens=5, finish_reason="stop")
  stats.note_step(active_slots=2, num_slots=4, prefill_tokens=8,
                  decode_tokens=2, step_time_s=0.02)
  stats.note_shed("x")
  state = json.loads(json.dumps(stats.state_dict()))
  twin = ServingStats()
  twin.load_state(state)
  assert twin.summary() == stats.summary()
  assert (fleet_summary([twin])["ttft_p99_s"]
          == fleet_summary([stats])["ttft_p99_s"])


def test_config_transport_validation():
  with pytest.raises(ValueError, match="transport"):
    epl.Config({"serving": {"router": {"transport": "carrier-pigeon"}}})
  with pytest.raises(ValueError, match="rpc_timeout_s"):
    epl.Config({"serving": {"router": {"rpc_timeout_s": 0.0}}})
  with pytest.raises(ValueError, match="rpc_retries"):
    epl.Config({"serving": {"router": {"rpc_retries": -1}}})
  conf = epl.Config()
  assert conf.serving.router.transport == "inproc"
  assert conf.serving.router.rpc_timeout_s > 0


def test_process_transport_requires_factory():
  with pytest.raises(ValueError, match="factory"):
    Router(num_replicas=1, config=_process_config())


# ----------------------------------------------- inproc transport (seam)


@pytest.mark.quick
def test_inproc_transport_default_fault_free_bit_exact_zero_recompile():
  """The transport seam changes NOTHING unless opted into: the default
  (explicitly named inproc) N=1 router stream is bit-identical to the
  bare engine, with the one compiled step intact (no transport-induced
  recompiles)."""
  prompts = _prompts(4)
  oracle = _oracle_outputs(prompts)
  model, params = tiny_gpt()
  router = Router(model, params, num_replicas=1,
                  config=epl.Config({"serving": {"router": {
                      "transport": "inproc"}}}),
                  num_slots=4, prefill_chunk=4)
  assert router.transport == "inproc"
  rep = router.replicas[0]
  assert isinstance(rep, InprocTransport)
  assert isinstance(rep, EngineReplica)   # byte-for-byte PR-8 replica
  assert rep.alive and rep.ensure_started() is False
  assert rep.rpc_counters() == {"rpc_retries": 0, "rpc_timeouts": 0,
                                "child_restarts": 0}
  for i, p in enumerate(prompts):
    router.submit(Request(uid=i, prompt=p, max_new_tokens=10))
  out = router.run()
  assert set(out) == set(oracle)
  for uid in oracle:
    assert np.array_equal(out[uid], oracle[uid]), uid
  assert rep.compile_count == 1
  counters = router.router_counters()
  assert counters["rpc_retries"] == counters["rpc_timeouts"] == 0.0
  router.close()


# ------------------------------------------ process transport: happy path


@pytest.mark.slow
def test_process_transport_serves_and_reaps_cleanly():
  prompts = _prompts(3)
  oracle = _oracle_outputs(prompts)
  router = Router(num_replicas=1, config=_process_config(),
                  factory=FACTORY, num_slots=4, prefill_chunk=4)
  rep = router.replicas[0]
  pid = rep.child_pid
  assert rep.alive and pid is not None
  for i, p in enumerate(prompts):
    assert router.submit(Request(uid=i, prompt=p, max_new_tokens=10))
  out = router.run()
  for uid in oracle:
    assert np.array_equal(out[uid], oracle[uid]), uid
  # Wire heartbeat carried the child's signals (compile-once included).
  beat = rep.wire_beat
  assert beat is not None and beat["compiles"] == 1
  assert beat["pid"] == pid
  assert rep.compile_count == 1
  # A malformed request is a CLIENT error, never replica death: it
  # crosses the wire, the child's validation rejects it, and the
  # ValueError surfaces to the caller with the replica still healthy
  # and the journal clean (no resurrection later).
  with pytest.raises(ValueError):
    router.submit(Request(uid="bad", prompt=np.zeros((0,), np.int32),
                          max_new_tokens=4))
  assert rep.alive and router.states() == ["healthy"]
  assert not rep.owns("bad")
  assert router.submit(Request(uid="bad", prompt=prompts[0],
                               max_new_tokens=4))
  router.run()
  assert router.finished["bad"].new_tokens == 4
  router.close()
  assert not rep.alive
  _assert_no_orphans([pid])


# --------------------------------------------- the acceptance: SIGKILL


@pytest.mark.quick
def test_process_sigkill_mid_decode_bit_exact_failover():
  """ISSUE 12 acceptance: SIGKILL one of two process replicas
  mid-decode — zero requests lost, every recovered stream bit-exact vs
  the fault-free oracle (recovered from the ROUTER-SIDE journal; the
  corpse cannot be asked anything), survivor compile count stays 1."""
  prompts = _prompts(6)
  oracle = _oracle_outputs(prompts)
  router = Router(num_replicas=2, config=_process_config(),
                  factory=FACTORY, num_slots=4, prefill_chunk=4)
  pids = [rep.child_pid for rep in router.replicas]
  for i, p in enumerate(prompts):
    assert router.submit(Request(uid=i, prompt=p, max_new_tokens=10))
  for _ in range(3):            # let decode get going on both children
    router.step()
  victim = router.replicas[0]
  survivor = router.replicas[1]
  assert victim.has_work, "victim must die MID-decode, not idle"
  killer = chaos.ProcessKiller(victim)
  killer.kill()
  router.run()
  assert router.failovers >= 1
  assert victim.exit_signal == signal.SIGKILL
  served = {i: np.asarray(router.finished[i].tokens)
            for i in range(len(prompts)) if i in router.finished}
  assert set(served) == set(oracle), "zero lost requests"
  for uid in oracle:
    assert np.array_equal(served[uid], oracle[uid]), uid
  # Compile sentinel silent: the survivor's fused step compiled ONCE —
  # journal replay is chunked prefill, never a new shape.
  assert survivor.compile_count == 1
  router.close()
  _assert_no_orphans(pids)


# ------------------------------------- ambiguous timeouts: exactly-once


@pytest.mark.slow
def test_submit_reply_dropped_then_retried_admits_exactly_once():
  """The reply to a submit is lost AFTER the child admitted it; the
  transport's jittered-backoff retry resends; the child's uid dedup
  returns the recorded verdict instead of double-admitting — the
  request is served exactly once, bit-exactly."""
  prompts = _prompts(2)
  oracle = _oracle_outputs(prompts)
  router = Router(num_replicas=1, config=_process_config(),
                  factory=FACTORY, num_slots=4, prefill_chunk=4)
  rep = router.replicas[0]
  pid = rep.child_pid
  # Drop the NEXT reply this parent reads (= the first submit's).
  dropper = chaos.ReplyDropper(rep, drop=(0,))
  assert router.submit(Request(uid=0, prompt=prompts[0],
                               max_new_tokens=10))
  assert dropper.dropped, "the submit reply must actually have dropped"
  assert rep.rpc_counters()["rpc_retries"] >= 1
  dropper.uninstall()
  assert router.submit(Request(uid=1, prompt=prompts[1],
                               max_new_tokens=10))
  out = router.run()
  assert set(out) == {0, 1}
  for uid in oracle:
    assert np.array_equal(out[uid], oracle[uid]), uid
  # Exactly once: the child admitted uid 0 a single time, so its token
  # count is the oracle's — a double admit would have shed or doubled.
  assert router.finished[0].new_tokens == oracle[0].size - prompts[0].size
  router.close()
  _assert_no_orphans([pid])


@pytest.mark.slow
def test_step_reply_lost_midflight_no_double_commit_on_replay():
  """A step reply vanishes mid-flight: the parent's journal watermark
  goes stale while the child committed tokens.  The replica is
  condemned (steps are never retried), fenced, and its requests replay
  on the survivor from the stale watermark — deterministic regeneration
  means the recovered stream is bit-exact with NO double-committed
  tokens."""
  prompts = _prompts(4)
  oracle = _oracle_outputs(prompts)
  router = Router(num_replicas=2, config=_process_config(),
                  factory=FACTORY, num_slots=4, prefill_chunk=4)
  pids = [rep.child_pid for rep in router.replicas]
  for i, p in enumerate(prompts):
    assert router.submit(Request(uid=i, prompt=p, max_new_tokens=10))
  for _ in range(2):
    router.step()
  victim = router.replicas[0]
  assert victim.has_work
  journal_before = {uid: len(e.generated)
                    for uid, e in victim._journal.items()}
  # Drop the victim's next step reply: its committed tokens never reach
  # the parent journal.
  chaos.ReplyDropper(victim, drop=(0,))
  router.run()
  assert router.failovers >= 1, "dropped step reply must condemn"
  assert victim.exit_signal == signal.SIGKILL    # fenced, not trusted
  served = {i: np.asarray(router.finished[i].tokens)
            for i in range(len(prompts)) if i in router.finished}
  assert set(served) == set(oracle)
  for uid in oracle:
    assert np.array_equal(served[uid], oracle[uid]), \
        (uid, journal_before)
  assert router.router_counters()["rpc_timeouts"] >= 1
  router.close()
  _assert_no_orphans(pids)


# ------------------------------------------------ transport observability


class _DuckReplica:
  """Minimal duck-typed transport for router-policy tests (no device)."""

  def __init__(self, index, rpc=None, die=False):
    self.index = index
    self.stats = None
    self.finished = {}
    self.has_work = die           # a dying replica owes work
    self.watchdog_timeouts = 0
    self.bad_steps = 0
    self.itl_ewma_s = 0.0
    self.num_slots = 4
    self.queue_depth = 0
    self.num_active = 0
    self.load = 0
    self.exit_signal = signal.SIGKILL if die else None
    self.child_pid = 4242 if die else None
    self._rpc = rpc or {"rpc_retries": 0, "rpc_timeouts": 0,
                        "child_restarts": 0}
    self._die = die

  def submit(self, req):
    return True

  def cancel(self, uid):
    return False

  def step(self):
    if self._die:
      raise ReplicaDeadError("chaos: child gone")
    return []

  def snapshot_requests(self):
    return list(getattr(self, "snaps", []))

  def evacuate(self):
    self.has_work = False
    snaps, self.snaps = list(getattr(self, "snaps", [])), []
    return snaps

  def restore_request(self, snap, front=False):
    if getattr(self, "restore_raises", False):
      raise ReplicaDeadError("chaos: died during restore")
    self.restored = getattr(self, "restored", [])
    self.restored.append(snap["request"]["uid"])
    return snap["request"]["uid"]

  def rpc_counters(self):
    return dict(self._rpc)

  def close(self):
    pass


def test_cancel_survives_replica_death_and_reaches_parked():
  """Review regression: a cancel whose replica dies mid-call must not
  surface a transport error (or be silently lost to a later failover
  replay) — the router fails the replica over and cancels the request
  wherever it landed."""
  def _snap(uid):
    return {"request": Request(uid=uid, prompt=np.asarray([3, 4], np.int32),
                               max_new_tokens=4).snapshot(),
            "generated": [7], "requeues": 0,
            "first_token_emitted": True, "submitted_at": 0.0}
  epl.init()
  rep = _DuckReplica(0, die=True)
  rep.snaps = [_snap("x")]

  def dying_cancel(uid):
    raise TransportTimeout("chaos: cancel reply lost")
  rep.cancel = dying_cancel
  router = Router(replicas=[rep])
  router.placement["x"] = 0
  assert router.cancel("x") is True          # no exception to the client
  assert router.finished["x"].finish_reason == "cancelled"
  assert np.array_equal(router.finished["x"].tokens,
                        np.asarray([3, 4, 7], np.int32))
  assert router.states() == ["down"]
  assert not router._parked                   # resolved, not resurrected
  router.close()


def test_failover_placement_survives_dying_target():
  """Review regression: a survivor that dies DURING snapshot placement
  must not take the remaining snapshots with it — the dead target is
  marked down and the rest land on the next survivor (or park); an
  outage delays, it never loses."""
  def _snap(uid):
    return {"request": Request(uid=uid, prompt=np.asarray([1, 2], np.int32),
                               max_new_tokens=4).snapshot(),
            "generated": [], "requeues": 0,
            "first_token_emitted": False, "submitted_at": 0.0}
  epl.init()
  dying = _DuckReplica(0, die=True)
  dying.snaps = [_snap("a"), _snap("b"), _snap("c")]
  bad_target = _DuckReplica(1)
  bad_target.restore_raises = True
  good_target = _DuckReplica(2)
  router = Router(replicas=[dying, bad_target, good_target])
  router.step()
  assert router.failovers == 1
  # All three snapshots reached the one target that survived placement.
  assert sorted(good_target.restored) == ["a", "b", "c"]
  assert router.states()[1] == "down"       # mid-placement death noticed
  assert len(router._parked) == 0
  assert {router.placement[u] for u in ("a", "b", "c")} == {2}
  router.close()


def test_replica_down_instant_carries_signal_and_rollup_rpc_counters(
    tmp_path):
  """Satellite 6: transport incidents ride the EXISTING schema — the
  fleet rollup carries summed rpc_retries/rpc_timeouts/child_restarts
  (so the SLO monitor and diagnostic bundles see them with zero new
  plumbing), and a dead replica emits a ``serving/replica_down`` trace
  instant naming the kill signal."""
  from easyparallellibrary_tpu.observability import trace as trace_lib
  from easyparallellibrary_tpu.observability import slo as slo_lib
  epl.init(epl.Config({"observability": {"enabled": True}}))
  try:
    tracer = trace_lib.ensure_configured()
    dead = _DuckReplica(0, rpc={"rpc_retries": 3, "rpc_timeouts": 1,
                                "child_restarts": 2}, die=True)
    ok = _DuckReplica(1)
    router = Router(replicas=[dead, ok])
    router.step()
    counters = router.router_counters()
    assert counters["rpc_retries"] == 3.0
    assert counters["rpc_timeouts"] == 1.0
    assert counters["child_restarts"] == 2.0
    rollup = router.fleet_summary()
    for key in ("rpc_retries", "rpc_timeouts", "child_restarts"):
      assert rollup[key] == counters[key]
    trace_path = str(tmp_path / "trace.json")
    assert tracer.export(trace_path)
    with open(trace_path) as f:
      events = json.load(f)["traceEvents"]
    downs = [e for e in events
             if e.get("name") == "serving/replica_down"]
    assert len(downs) == 1
    assert downs[0]["args"]["signal"] == "SIGKILL"
    assert downs[0]["args"]["replica"] == 0
    assert downs[0]["args"]["pid"] == 4242
  finally:
    trace_lib.reset()
    slo_lib.reset()


# ----------------------------------------------- stalls, probes, orphans


@pytest.mark.slow
def test_process_stall_sigstop_condemns_fences_and_fails_over():
  """A SIGSTOPped child is a genuinely frozen process: the wire
  deadline trips, the replica is condemned (never retried — the stall
  might end mid-apply), fenced with SIGKILL so it can never
  double-serve, and its requests finish bit-exactly on the survivor."""
  prompts = _prompts(4)
  oracle = _oracle_outputs(prompts)
  router = Router(num_replicas=2, config=_process_config(rpc_retries=0),
                  factory=FACTORY, num_slots=4, prefill_chunk=4)
  pids = [rep.child_pid for rep in router.replicas]
  # Warm both children under the generous default deadline (the first
  # step carries XLA compilation), THEN tighten the wire deadline so
  # the stall — not the compile — is what trips it.
  for k, rep in enumerate(router.replicas):
    rep.submit(Request(uid=f"warm{k}", prompt=prompts[0],
                       max_new_tokens=2))
  router.run()
  for rep in router.replicas:
    rep.rpc_timeout_s = 2.0
  for i, p in enumerate(prompts):
    assert router.submit(Request(uid=i, prompt=p, max_new_tokens=10))
  for _ in range(2):
    router.step()
  victim = router.replicas[0]
  assert victim.has_work
  staller = chaos.ProcessStaller(victim)
  staller.stall()
  router.run()
  assert router.failovers >= 1
  assert router.router_counters()["rpc_timeouts"] >= 1
  assert victim.exit_signal == signal.SIGKILL    # fenced while stopped
  staller.resume()            # post-fence SIGCONT: arrives at a corpse
  served = {i: np.asarray(router.finished[i].tokens)
            for i in range(len(prompts)) if i in router.finished}
  assert set(served) == set(oracle)
  for uid in oracle:
    assert np.array_equal(served[uid], oracle[uid]), uid
  router.close()
  _assert_no_orphans(pids)


@pytest.mark.slow
def test_breaker_probe_respawns_dead_child():
  """After the breaker cooldown a probe must RESPAWN the dead child
  (fresh process, fresh pid, cold engine) and serve traffic on it."""
  router = Router(num_replicas=1,
                  config=_process_config(down_after=1.0,
                                         suspect_after=0.5),
                  factory=FACTORY, num_slots=2, prefill_chunk=4)
  rep = router.replicas[0]
  old_pid = rep.child_pid
  prompt = _prompts(1)[0]
  oracle = _oracle_outputs([prompt], max_new=8)
  assert router.submit(Request(uid=0, prompt=prompt, max_new_tokens=8))
  router.step()
  chaos.ProcessKiller(rep).kill()
  router.step()               # death observed -> down; request parked
  assert router.states() == ["down"]
  deadline = time.monotonic() + 30.0
  while router.states() != ["healthy"] and time.monotonic() < deadline:
    time.sleep(0.1)
    router.step()             # breaker cooldown elapses -> probe
  assert router.states() == ["healthy"]
  assert rep.child_restarts == 1
  assert rep.child_pid != old_pid
  assert router.router_counters()["child_restarts"] == 1.0
  out = router.run()          # the parked request resumes, bit-exactly
  assert np.array_equal(out[0], oracle[0])
  pids = [old_pid, rep.child_pid]
  router.close()
  _assert_no_orphans(pids)


@pytest.mark.slow
def test_wire_version_mismatch_fails_loudly(monkeypatch):
  before = set(transport_lib._LIVE_CHILDREN)
  monkeypatch.setattr(transport_lib, "WIRE_VERSION", 999)
  with pytest.raises(Exception, match="wire version"):
    ProcessTransport(0, FACTORY, config=_process_config(),
                     engine_kwargs={"num_slots": 2, "prefill_chunk": 4})
  # The half-born child was fenced at the failed init, not leaked.
  _assert_no_orphans(list(set(transport_lib._LIVE_CHILDREN) - before))


def test_atexit_reaper_kills_live_children():
  """A dying router must never leak children: every spawned child is
  registered with the atexit reaper, and reaping is idempotent."""
  tr = ProcessTransport(0, FACTORY, config=_process_config(),
                        engine_kwargs={"num_slots": 2,
                                       "prefill_chunk": 4})
  pid = tr.child_pid
  assert pid in transport_lib._LIVE_CHILDREN
  transport_lib._reap_orphans()
  _assert_no_orphans([pid])
  assert pid not in transport_lib._LIVE_CHILDREN
  transport_lib._reap_orphans()   # idempotent on an empty registry


@pytest.mark.slow
def test_process_graceful_drain_migrates_over_rpc():
  """Drain-timeout migration of a LIVE process replica goes through the
  graceful evacuate RPC (exact scheduler snapshots, child keeps
  running) — the journal fence is only for the dead."""
  prompts = _prompts(4)
  oracle = _oracle_outputs(prompts)
  router = Router(num_replicas=2, config=_process_config(),
                  factory=FACTORY, num_slots=4, prefill_chunk=4)
  pids = [rep.child_pid for rep in router.replicas]
  for i, p in enumerate(prompts):
    assert router.submit(Request(uid=i, prompt=p, max_new_tokens=10))
  for _ in range(2):
    router.step()
  router.drain(0, timeout_s=0.0)   # migrate immediately
  router.run()
  assert router.replicas[0].alive, "graceful drain must not fence"
  assert router.migrated_requests >= 1
  served = {i: np.asarray(router.finished[i].tokens)
            for i in range(len(prompts)) if i in router.finished}
  assert set(served) == set(oracle)
  for uid in oracle:
    assert np.array_equal(served[uid], oracle[uid]), uid
  router.close()
  _assert_no_orphans(pids)
