"""High-level fit loop: training, periodic checkpointing, auto-resume."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import optax
from flax import linen as nn

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu import ops
from easyparallellibrary_tpu.parallel import (
    TrainState, create_sharded_train_state, make_train_step, parallelize)
from easyparallellibrary_tpu.runtime.loop import fit
from easyparallellibrary_tpu.runtime.saver import latest_step


class Net(nn.Module):
  @nn.compact
  def __call__(self, x):
    return ops.Dense(1, parallel="none")(jnp.tanh(
        ops.Dense(8, parallel="none")(x)))


def _setup():
  env = epl.init()
  mesh = epl.current_plan().build_mesh()
  model = Net()
  r = np.random.RandomState(0)
  x = jnp.asarray(r.randn(16, 4), jnp.float32)
  y = jnp.asarray(r.randn(16, 1), jnp.float32)

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, x)["params"],
                             tx=optax.adam(1e-2))

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))

  def loss_fn(params, batch, rng):
    pred = model.apply({"params": params}, batch["x"])
    return jnp.mean((pred - batch["y"]) ** 2), {}

  step = parallelize(make_train_step(loss_fn), mesh, shardings)
  return state, shardings, step, {"x": x, "y": y}


def test_fit_trains_and_checkpoints(tmp_path):
  state, shardings, step, batch = _setup()
  ckpt = str(tmp_path / "ck")
  state, metrics = fit(step, state, [batch], num_steps=10,
                       checkpoint_dir=ckpt, checkpoint_every=5,
                       log_every=0, shardings=shardings)
  assert int(state.step) == 10
  assert latest_step(ckpt) == 10
  assert np.isfinite(float(metrics["loss"]))


def test_fit_resumes_from_checkpoint(tmp_path):
  state, shardings, step, batch = _setup()
  ckpt = str(tmp_path / "ck")
  state, _ = fit(step, state, [batch], num_steps=6, checkpoint_dir=ckpt,
                 checkpoint_every=3, log_every=0, shardings=shardings)
  params_after_6 = jax.tree_util.tree_map(np.asarray,
                                          jax.device_get(state.params))

  # Fresh state (step 0) resumes from the step-6 checkpoint and runs 6..8.
  state2, shardings2, step2, _ = _setup()
  state2, _ = fit(step2, state2, [batch], num_steps=8, checkpoint_dir=ckpt,
                  log_every=0, shardings=shardings2)
  assert int(state2.step) == 8


def test_evaluate_and_train_and_evaluate(tmp_path):
  from easyparallellibrary_tpu.runtime.loop import evaluate, train_and_evaluate
  state, shardings, step, batch = _setup()

  def eval_fn(state, b, rng):
    pred = state.apply_fn({"params": state.params}, b["x"])
    return {"mse": jnp.mean((pred - b["y"]) ** 2)}

  m0 = evaluate(eval_fn, state, [batch, batch])
  assert "mse" in m0 and np.isfinite(m0["mse"])

  state, metrics = train_and_evaluate(
      step, eval_fn, state, [batch], [batch],
      num_steps=6, eval_every=3, log_every=0)
  assert int(state.step) == 6
  assert "eval_mse" in metrics
  assert metrics["eval_mse"] < m0["mse"]


def test_metrics_writer(tmp_path):
  import json
  from easyparallellibrary_tpu.utils.metrics_writer import MetricsWriter
  path = str(tmp_path / "metrics.jsonl")
  with MetricsWriter(path) as w:
    w.write(1, {"loss": jnp.float32(2.5), "note": "x"})
    w.write(2, {"loss": 1.5})
  lines = [json.loads(l) for l in open(path)]
  assert lines[0]["loss"] == 2.5 and lines[1]["step"] == 2


def test_preemption_checkpoint(tmp_path):
  """SIGTERM mid-training -> checkpoint written -> resume works."""
  import signal as _signal
  from easyparallellibrary_tpu.runtime.loop import fit as _fit
  state, shardings, step, batch = _setup()
  ckpt = str(tmp_path / "ck")

  class SignalOnce:
    """Iterable that raises SIGTERM in-process after 3 batches."""
    def __init__(self):
      self.n = 0
    def __iter__(self):
      return self
    def __next__(self):
      self.n += 1
      if self.n == 4:
        os.kill(os.getpid(), _signal.SIGTERM)
      return batch

  import os
  with np.testing.assert_raises(SystemExit):
    _fit(step, state, SignalOnce(), num_steps=100, checkpoint_dir=ckpt,
         log_every=0, shardings=shardings)
  saved = latest_step(ckpt)
  assert saved is not None and 3 <= saved <= 5
  # Resume completes the run.
  state2, shardings2, step2, _ = _setup()
  state2, _ = _fit(step2, state2, [batch], num_steps=saved + 2,
                   checkpoint_dir=ckpt, log_every=0, shardings=shardings2)
  assert int(state2.step) == saved + 2


def test_fit_resume_restores_opt_state(tmp_path):
  """Resume must restore Adam moments, not just params."""
  state, shardings, step, batch = _setup()
  ckpt = str(tmp_path / "ck")
  state, _ = fit(step, state, [batch], num_steps=6, checkpoint_dir=ckpt,
                 checkpoint_every=6, log_every=0, shardings=shardings)
  mu_after_6 = np.asarray(jax.device_get(
      jax.tree_util.tree_leaves(state.opt_state)[0]))

  state2, shardings2, step2, _ = _setup()
  # Resume: opt_state should come back non-zero (Adam mu after 6 steps).
  from easyparallellibrary_tpu.runtime import saver as saver_lib
  restored, _ = saver_lib.restore_checkpoint(
      ckpt, target={"params": state2.params, "opt_state": state2.opt_state})
  mu_restored = np.asarray(
      jax.tree_util.tree_leaves(restored["opt_state"])[0])
  np.testing.assert_allclose(mu_restored, mu_after_6, rtol=1e-6)
  assert float(np.max(np.abs(mu_restored))) > 0


def test_fit_iterator_factory_multi_epoch():
  state, shardings, step, batch = _setup()
  calls = {"n": 0}

  def factory():
    calls["n"] += 1
    return iter([batch, batch])  # 2 batches per "epoch"

  state, _ = fit(step, state, factory, num_steps=5, log_every=0)
  assert int(state.step) == 5
  assert calls["n"] >= 3  # re-created for each epoch


def test_fit_exhausted_iterator_raises_clear_error():
  state, shardings, step, batch = _setup()
  one_shot = iter([batch, batch])
  with np.testing.assert_raises(RuntimeError):
    fit(step, state, one_shot, num_steps=5, log_every=0)


def test_fit_resume_passes_start_step_to_data_factory(tmp_path):
  """Resuming from a checkpoint at step N must hand the data factory
  start_step=N (mid-epoch input-position resume); epoch restarts within
  a run hand it start_step=0."""
  state, shardings, step, batch = _setup()
  ckpt = str(tmp_path / "ck")
  calls = []

  def factory(start_step=0):
    calls.append(start_step)
    return [batch, batch]          # 2 batches per "epoch"

  state, _ = fit(step, state, factory, num_steps=5, checkpoint_dir=ckpt,
                 checkpoint_every=5, log_every=0, shardings=shardings)
  # First iterator at step 0, then epoch restarts at steps 2 and 4.
  assert calls == [0, 0, 0]

  state2, shardings2, step2, _ = _setup()
  calls.clear()
  state2, _ = fit(step2, state2, factory, num_steps=7, checkpoint_dir=ckpt,
                  log_every=0, shardings=shardings2)
  # Resumed at step 5 → factory told to start there; the following epoch
  # restart goes back to 0.
  assert calls[0] == 5
  assert all(c == 0 for c in calls[1:])
  assert int(state2.step) == 7


def test_fit_plain_factory_still_works():
  state, _, step, batch = _setup()

  def factory():
    return [batch]

  state, metrics = fit(step, state, factory, num_steps=3, log_every=0)
  assert int(state.step) == 3


@pytest.mark.slow
def test_tensorboard_writer_renders_in_stock_tensorboard(tmp_path):
  """VERDICT r2 item 8 done-criterion: the events file written by
  TensorBoardWriter loads in stock TensorBoard's own reader."""
  from easyparallellibrary_tpu.utils.metrics_writer import TensorBoardWriter

  logdir = str(tmp_path / "tb")
  with TensorBoardWriter(logdir) as w:
    w.write(1, {"loss": jnp.float32(2.5), "mfu": 0.41, "note": "cfg-a"})
    w.write(2, {"loss": 1.25, "mfu": 0.43})

  from tensorboard.backend.event_processing.event_accumulator import (
      EventAccumulator)
  acc = EventAccumulator(logdir)
  acc.Reload()
  assert "loss" in acc.Tags()["scalars"]
  scalars = acc.Scalars("loss")
  assert [s.step for s in scalars] == [1, 2]
  assert scalars[0].value == 2.5 and scalars[1].value == 1.25
  import pytest
  assert [s.value for s in acc.Scalars("mfu")] == pytest.approx(
      [0.41, 0.43])


def test_fit_feeds_metrics_writer(tmp_path):
  """fit(metrics_writer=...) streams every step's merged metrics."""
  import json
  from easyparallellibrary_tpu.runtime.loop import fit
  from easyparallellibrary_tpu.utils.metrics_writer import MetricsWriter

  state, shardings, step, batch = _setup()
  path = str(tmp_path / "m.jsonl")
  with MetricsWriter(path) as w:
    state, _ = fit(step, state, [batch], num_steps=3, log_every=0,
                   metrics_writer=w)
  lines = [json.loads(l) for l in open(path)]
  assert [l["step"] for l in lines] == [1, 2, 3]
  assert all("loss" in l or "mse" in l for l in lines)
