"""Blue/green checkpoint rollout (serving/rollout.py): zero-downtime
cutover with an SLO-watched canary and automatic rollback.

The quick contract pins: a full rollout under live traffic loses zero
requests, every response is attributable to exactly one checkpoint
version, and all compile counts stay <= 1 per replica; a canary-scoped
SLO breach triggers automatic rollback with the blue stream bit-exact
vs a never-rolled fleet; and the fault-free guard — rollout enabled
but never invoked is bit-identical to the baseline with zero
actuations.  The policy units pin the version-aware dispatch split and
the cross-version replay fences (scheduler, transport, placement).
`make chaos-rollout` runs the slow mid-rollout SIGKILL episode.
"""

import json
import os
import time

import numpy as np
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.observability import slo as slo_lib
from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.observability.registry import MetricRegistry
from easyparallellibrary_tpu.observability.slo import SLOMonitor, SLORule
from easyparallellibrary_tpu.runtime.saver import (
    checkpoint_fingerprint, save_checkpoint)
from easyparallellibrary_tpu.serving import Request, Router
from easyparallellibrary_tpu.serving.prefix_cache import (
    PrefixCache, block_prefix_keys)
from easyparallellibrary_tpu.serving.scheduler import FCFSScheduler
from easyparallellibrary_tpu.testing.factories import tiny_gpt

FACTORY = "easyparallellibrary_tpu.testing.factories:tiny_gpt"


@pytest.fixture(autouse=True)
def _drop_ambient_observability():
  yield
  trace_lib.reset()
  slo_lib.reset()


def _prompts(n, lengths=(5, 3, 7, 2), vocab=64, seed=0):
  r = np.random.RandomState(seed)
  return [r.randint(0, vocab, (lengths[i % len(lengths)],)).astype(
      np.int32) for i in range(n)]


def _oracle(model, params, prompt, max_new):
  import jax.numpy as jnp
  from easyparallellibrary_tpu.models.gpt import generate
  return np.asarray(
      generate(model, params, jnp.asarray(prompt)[None], max_new))[0]


class FakeClock:
  def __init__(self, t=0.0):
    self.t = t

  def __call__(self):
    return self.t

  def advance(self, dt):
    self.t += dt


# ------------------------------------------------------- config & units


def test_rollout_config_validation():
  with pytest.raises(ValueError, match="canary_frac"):
    epl.Config({"serving": {"rollout": {"canary_frac": 0.0}}})
  with pytest.raises(ValueError, match="canary_frac"):
    epl.Config({"serving": {"rollout": {"canary_frac": 1.5}}})
  with pytest.raises(ValueError, match="min_replicas"):
    epl.Config({"serving": {"rollout": {"min_replicas": 0}}})
  with pytest.raises(ValueError, match="spawn_timeout_s"):
    epl.Config({"serving": {"rollout": {"spawn_timeout_s": 0.0}}})
  with pytest.raises(ValueError, match="canary_hold_s"):
    epl.Config({"serving": {"rollout": {"canary_hold_s": -1.0}}})
  conf = epl.Config({"serving": {"rollout": {"rules": "ttft_p99"}}})
  assert conf.serving.rollout.rules == ("ttft_p99",)
  assert conf.serving.rollout.enabled is False


def test_prefix_keys_version_salted():
  """Version 0 is byte-identical to the pre-versioning hash (every
  existing affinity/cache pin keeps passing); any other version
  produces a DISJOINT key space at every depth — blue-era affinity
  entries can never name a green replica."""
  p = np.arange(16, dtype=np.int32)
  assert block_prefix_keys(p, 4) == block_prefix_keys(p, 4, version=0)
  v0, v1 = (block_prefix_keys(p, 4, version=v) for v in (0, 1))
  assert len(v0) == len(v1)
  assert not set(v0) & set(v1)
  assert (block_prefix_keys(p, 4, version=1)
          != block_prefix_keys(p, 4, version=2))
  short = np.asarray([1, 2], np.int32)          # sub-block fallback key
  assert (block_prefix_keys(short, 4, version=0)
          != block_prefix_keys(short, 4, version=1))


def test_prefix_cache_version_scoped_roots():
  """Two caches at different checkpoint versions key their radix roots
  disjointly: identical token content registered under v1 is invisible
  to a v2 match (block content under different weights is different KV
  — reuse across versions would be silent corruption)."""
  from easyparallellibrary_tpu.serving import BlockAllocator
  tokens = np.arange(1, 13, dtype=np.int32)       # 3 full blocks
  alloc = BlockAllocator(num_blocks=32, block_size=4)
  c0 = PrefixCache(alloc, block_size=4)
  c1 = PrefixCache(alloc, block_size=4, version=1)
  assert c0.version == 0 and c1.version == 1
  owned0 = [alloc.alloc() for _ in range(3)]
  owned1 = [alloc.alloc() for _ in range(3)]
  assert c0.register(tokens, 3, owned0) == 3
  assert c1.register(tokens, 3, owned1) == 3
  # Each cache matches only its OWN version's blocks for identical
  # token content — the roots live in disjoint key spaces.
  assert c0.match(tokens) == owned0[:2]
  assert c1.match(tokens) == owned1[:2]
  # Version 0 stays byte-compatible: an unversioned cache is version 0.
  assert PrefixCache(alloc, block_size=4).version == 0


def test_scheduler_refuses_cross_version_restore():
  sched = FCFSScheduler(num_slots=2, prefill_chunk=4, max_seq_len=32,
                        checkpoint_version=1)
  req = Request(uid="r1", prompt=np.asarray([1, 2, 3], np.int32),
                max_new_tokens=4, checkpoint_version=1)
  snap = {"request": req.snapshot(), "generated": [7],
          "requeues": 0, "first_token_emitted": True,
          "submitted_at": 0.0}
  # Same version restores; so does an unpinned (None) legacy snapshot.
  assert sched.restore_request(snap) == "r1"
  legacy = dict(snap)
  legacy["request"] = dict(snap["request"], checkpoint_version=None,
                           uid="r2")
  assert sched.restore_request(legacy) == "r2"
  wrong = dict(snap)
  wrong["request"] = dict(snap["request"], checkpoint_version=2,
                          uid="r3")
  with pytest.raises(ValueError, match="cross-version restore refused"):
    sched.restore_request(wrong)


def test_process_transport_refuses_cross_version_restore_parent_side():
  """The parent-side fence fires BEFORE journaling or wire traffic: a
  cross-version snapshot never reaches the child and never poisons the
  crash journal."""
  from easyparallellibrary_tpu.serving.transport import ProcessTransport
  rep = ProcessTransport(
      0, FACTORY, config=epl.Config(),
      engine_kwargs={"checkpoint_version": 3}, start=False)
  assert rep.checkpoint_version == 3     # engine-kwargs fallback
  req = Request(uid="x", prompt=np.asarray([1, 2], np.int32),
                max_new_tokens=2, checkpoint_version=2)
  snap = {"request": req.snapshot(), "generated": [],
          "requeues": 0, "first_token_emitted": False,
          "submitted_at": 0.0}
  with pytest.raises(ValueError, match="cross-version restore refused"):
    rep.restore_request(snap)
  assert not rep._journal, "refused restore must not be journaled"


class _VersionedFake:
  """Duck-typed replica with a pinned checkpoint version for pure
  dispatch/placement policy tests."""

  def __init__(self, index, version=0):
    self.index = index
    self.checkpoint_version = version
    self.finished = {}
    self.has_work = False
    self.num_slots = 4
    self.stats = None
    self.watchdog_timeouts = 0
    self.bad_steps = 0
    self.itl_ewma_s = 0.0
    self.restored = []

  load = property(lambda self: len(self.restored))
  queue_depth = property(lambda self: 0)
  num_active = property(lambda self: 0)

  def submit(self, req):
    return True

  def cancel(self, uid):
    return False

  def step(self):
    return []

  def evacuate(self):
    return []

  def restore_request(self, snap, front=False):
    self.restored.append(snap["request"]["uid"])
    return snap["request"]["uid"]

  def close(self):
    pass


def _pinned_snap(uid, version):
  req = Request(uid=uid, prompt=np.asarray([1, 2, 3], np.int32),
                max_new_tokens=2, checkpoint_version=version)
  return {"request": req.snapshot(), "generated": [], "requeues": 0,
          "first_token_emitted": False, "submitted_at": 0.0}


def test_version_weight_split_is_deterministic_and_exact():
  """The deficit split admits EXACTLY weight-share of requests per
  version, deterministically (no RNG): 10% green over 20 admissions is
  2 green, and a replay of the same sequence splits identically."""
  replicas = [_VersionedFake(0, 0), _VersionedFake(1, 0),
              _VersionedFake(2, 1)]
  router = Router(replicas=replicas, clock=FakeClock())
  prompts = _prompts(20, seed=5)

  def drive():
    router.set_version_weights({0: 0.9, 1: 0.1})
    picks = []
    for i, p in enumerate(prompts):
      idx, _reason = router._choose(p)
      picks.append(router._replica_version(idx))
    return picks

  picks = drive()
  assert picks.count(1) == 2 and picks.count(0) == 18
  assert picks == drive(), "the split must replay identically"
  # Weights cleared -> version-blind dispatch, counters reset.
  router.set_version_weights(None)
  assert router._version_weights is None
  assert router._version_dispatched == {}
  # A weighted version with NO live replica degrades to the rest of
  # the fleet instead of shedding.
  router.set_version_weights({7: 1.0})
  idx, _ = router._choose(prompts[0])
  assert idx is not None
  router.close()


def test_placement_respects_version_pins_and_parks_orphans():
  """Failover placement: a version-pinned snapshot lands only on a
  SAME-version target; with no same-version target it parks (delayed,
  never replayed cross-version) and flushes the moment its version has
  a live replica again."""
  replicas = [_VersionedFake(0, 1), _VersionedFake(1, 1),
              _VersionedFake(2, 2)]
  router = Router(replicas=replicas, clock=FakeClock())
  placed = router._place_snapshots(
      [_pinned_snap("a", 1), _pinned_snap("b", 2),
       _pinned_snap("c", None), _pinned_snap("d", 3)],
      targets=[0, 1, 2])
  assert placed == 3
  blue_restored = replicas[0].restored + replicas[1].restored
  assert "a" in blue_restored and "a" not in replicas[2].restored
  assert replicas[2].restored == ["b"]
  assert "c" in blue_restored + replicas[2].restored
  # The v3 orphan parked; it does NOT churn while no v3 replica exists.
  assert [s["request"]["uid"] for s in router._parked] == ["d"]
  router._flush_parked()
  assert [s["request"]["uid"] for s in router._parked] == ["d"]
  # A v3 replica appears: the orphan flushes onto it.
  replicas.append(_VersionedFake(3, 3))
  router.replicas.append(replicas[3])
  router.health.append(router._make_health(3))
  router._flush_parked()
  assert router._parked == []
  assert replicas[3].restored == ["d"]
  router.close()


def test_rollout_begin_refuses_bad_checkpoint(tmp_path):
  """Validation runs BEFORE any green replica exists: a geometry
  mismatch or a corrupt shard fails begin() in milliseconds and the
  fleet is untouched."""
  import jax
  epl.init()
  config = epl.Config({"serving": {"rollout": {"enabled": True}}})
  model, params = tiny_gpt()
  router = Router(model, params, num_replicas=1, config=config,
                  num_slots=2, prefill_chunk=4)
  assert router.rollout is not None and router.rollout.state == "idle"
  # Wrong geometry: truncate one leaf before saving.
  broken = jax.tree_util.tree_map(lambda x: x, params)
  flat, treedef = jax.tree_util.tree_flatten(broken)
  flat[0] = np.asarray(flat[0])[..., :1]
  broken = jax.tree_util.tree_unflatten(treedef, flat)
  bad_dir = str(tmp_path / "bad")
  save_checkpoint(bad_dir, broken, step=1)
  with pytest.raises(ValueError, match="rollout validation failed"):
    router.rollout.begin(bad_dir)
  # Corrupt shard: the checksum chain rejects it.
  good_dir = str(tmp_path / "good")
  path = save_checkpoint(good_dir, params, step=1)
  shard = next(f for f in os.listdir(path) if f.endswith(".npz"))
  with open(os.path.join(path, shard), "r+b") as f:
    f.seek(0)
    f.write(b"\x00" * 8)
  with pytest.raises((FileNotFoundError, ValueError)):
    router.rollout.begin(good_dir)
  assert router.rollout.state == "idle"
  assert len(router.replicas) == 1
  assert router.rollout.counters()["rollout_started"] == 0.0
  router.close()


def test_saver_records_and_verifies_params_fingerprint(tmp_path):
  """index.json carries a params fingerprint (tree structure + shapes +
  per-shard sha256 rollup) recorded at save time; verify_checkpoint —
  and therefore every restore_params walk — recomputes it, so an
  edited index (leaves remapped over intact shards) is rejected."""
  from easyparallellibrary_tpu.runtime.saver import (
      params_fingerprint, verify_checkpoint)
  epl.init()
  _, params = tiny_gpt()
  path = save_checkpoint(str(tmp_path / "ck"), params, step=3)
  with open(os.path.join(path, "index.json")) as f:
    index = json.load(f)
  assert index["params_fingerprint"] == params_fingerprint(index)
  fingerprint, step = checkpoint_fingerprint(str(tmp_path / "ck"))
  assert fingerprint == index["params_fingerprint"] and step == 3
  ok, reason = verify_checkpoint(path)
  assert ok, reason
  # Tamper with the index only (shards intact): the leaf->shape map no
  # longer matches the recorded fingerprint.
  leaves = index["leaves"]
  key = sorted(leaves)[0]
  leaves[key] = dict(leaves[key], shape=[9999])
  with open(os.path.join(path, "index.json"), "w") as f:
    json.dump(index, f)
  ok, reason = verify_checkpoint(path)
  assert not ok and "fingerprint" in reason


# ----------------------------------------- quick: the rollout contract


def _rollout_config(**rollout):
  rollout.setdefault("enabled", True)
  rollout.setdefault("canary_frac", 0.5)
  rollout.setdefault("canary_hold_s", 1.0)
  rollout.setdefault("min_replicas", 2)
  rollout.setdefault("drain_timeout_s", 60.0)
  return epl.Config({"serving": {"rollout": rollout}})


def _pump(router, clock, until, deadline_s=90.0, dt=0.05,
          submit=None):
  """Step the fleet (advancing the fake clock) until ``until()`` or a
  wall-clock deadline — real threads (the green spawner) need real
  time to post outcomes."""
  deadline = time.monotonic() + deadline_s
  while not until():
    assert time.monotonic() < deadline, (
        f"rollout stuck in state {router.rollout.state!r}")
    if submit is not None:
      submit()
    router.step()
    clock.advance(dt)
    time.sleep(0.002)


@pytest.mark.quick
def test_full_rollout_zero_loss_single_version_attribution(tmp_path):
  """The rollout contract: under live traffic a full blue->green
  rollout loses ZERO requests, every response is attributable to
  exactly one checkpoint version, compile counts stay <= 1 per
  replica, and the fleet lands on green (recipe included)."""
  epl.init()
  config = _rollout_config()
  model, params = tiny_gpt()
  ckpt_dir = str(tmp_path / "green")
  save_checkpoint(ckpt_dir, params, step=7)
  clock = FakeClock()
  router = Router(model, params, num_replicas=2, config=config,
                  clock=clock, num_slots=2, prefill_chunk=4)
  prompts = _prompts(24, seed=9)
  max_new = 5
  admitted_version = {}
  uid_ctr = [0]

  def submit_one():
    uid = uid_ctr[0]
    if uid >= len(prompts):
      return
    uid_ctr[0] += 1
    assert router.submit(Request(uid=uid, prompt=prompts[uid],
                                 max_new_tokens=max_new))
    # Attribution at admission: complete-in-place + version-pinned
    # failover guarantee the request retires on this version.
    admitted_version[uid] = router._replica_version(
        router.placement[uid])

  for _ in range(4):
    submit_one()
  router.step()
  green_version = router.rollout.begin(ckpt_dir)
  assert green_version == 1 and router.rollout.state == "spawning"
  _pump(router, clock,
        until=lambda: router.rollout.state == "canary",
        submit=submit_one)
  assert len(router.replicas) == 4          # 2 blue + 2 green
  assert router._version_weights == {0: 0.5, 1: 0.5}
  # Canary traffic flows to BOTH versions while the hold elapses.
  _pump(router, clock,
        until=lambda: router.rollout.state != "canary",
        submit=submit_one)
  assert router.rollout.state in ("draining_blue", "idle")
  _pump(router, clock,
        until=lambda: router.rollout.state == "idle",
        submit=submit_one)
  while uid_ctr[0] < len(prompts):          # post-cutover traffic
    submit_one()
  router.run()
  # Zero lost: every admitted request retired with its full stream.
  assert sorted(router.finished) == sorted(range(len(prompts)))
  for uid in range(len(prompts)):
    fin = router.finished[uid]
    assert fin.finish_reason == "length", (uid, fin.finish_reason)
    np.testing.assert_array_equal(
        fin.tokens, _oracle(model, params, prompts[uid], max_new),
        err_msg=f"req {uid}")
  # Exactly-one-version attribution, and both versions actually served.
  versions = set(admitted_version.values())
  assert versions == {0, 1}
  post_cutover = [u for u in admitted_version
                  if admitted_version[u] == 1]
  assert len(post_cutover) >= 2
  # Compile-once fleet-wide (greens included).
  for rep in router.replicas:
    assert rep.engine._step_fn._cache_size() <= 1
    assert rep.engine._compile_sentinel.recompiles == 0
  # The fleet LANDED on green: version advanced, weights cleared, blue
  # drained, and the recipe now builds green replicas.
  assert router._fleet_version == 1
  assert router._version_weights is None
  assert [h.state for h in router.health] == [
      "draining", "draining", "healthy", "healthy"]
  assert router._replica_spec["engine_kwargs"][
      "checkpoint_version"] == 1
  assert router.rollout.counters()["rollout_completed"] == 1.0
  assert router.rollout.counters()["rollout_active"] == 0.0
  router.close()


@pytest.mark.quick
def test_canary_breach_rolls_back_blue_bit_exact(tmp_path):
  """A canary-scoped SLO breach (green's per-version stream) triggers
  automatic rollback: green drains with its in-flight canary requests
  completing in place, blue admission restores, and every
  blue-attributed stream is bit-exact vs a never-rolled fleet — even
  though the green checkpoint holds DIFFERENT weights."""
  import jax
  epl.init()
  model, params = tiny_gpt()
  # Green is a genuinely different model (perturbed weights) with the
  # same geometry — the canary must not corrupt any blue stream.
  perturbed = jax.tree_util.tree_map(
      lambda x: np.asarray(x) * 1.5, params)
  ckpt_dir = str(tmp_path / "green")
  save_checkpoint(ckpt_dir, perturbed, step=2)
  prompts = _prompts(16, seed=13)
  max_new = 4

  def drive(router, clock, roll):
    admitted_version = {}
    uid_ctr = [0]

    def submit_one():
      uid = uid_ctr[0]
      if uid >= len(prompts):
        return
      uid_ctr[0] += 1
      assert router.submit(Request(uid=uid, prompt=prompts[uid],
                                   max_new_tokens=max_new))
      admitted_version[uid] = router._replica_version(
          router.placement[uid])

    for _ in range(4):
      submit_one()
    router.step()
    if roll:
      router.rollout.begin(ckpt_dir)
      _pump(router, clock,
            until=lambda: router.rollout.state == "canary",
            submit=submit_one)
      for _ in range(4):
        submit_one()              # canary traffic on both versions
      router.step()
      # The green-scoped breach stream fires: the monitor's bare-name
      # rule suffix-matches the per-version key the router publishes.
      slo_lib.get_monitor().observe(
          router.steps, {"serving/fleet/v1/ttft_p99_s": 99.0})
      _pump(router, clock,
            until=lambda: router.rollout.state != "canary")
      assert router.rollout.state == "rolling_back"
      _pump(router, clock,
            until=lambda: router.rollout.state == "idle")
    while uid_ctr[0] < len(prompts):
      submit_one()
    router.run()
    return admitted_version

  def make_router(clock):
    config = epl.Config({
        "serving": {"rollout": {
            "enabled": True, "canary_frac": 0.5,
            "canary_hold_s": 1000.0,   # only the breach ends the canary
            "min_replicas": 2, "drain_timeout_s": 60.0}},
        "observability": {"slo": {"enabled": True,
                                  "ttft_p99_s": 0.5}}})
    epl.init(config)
    return Router(model, params, num_replicas=2, config=config,
                  clock=clock, num_slots=2, prefill_chunk=4), config

  base_router, _ = make_router(FakeClock())
  base_attr = drive(base_router, FakeClock(), roll=False)
  base = {u: f.tokens for u, f in base_router.finished.items()}
  base_router.close()
  slo_lib.reset()

  clock = FakeClock()
  router, _ = make_router(clock)
  attr = drive(router, clock, roll=True)
  rolled = {u: f.tokens for u, f in router.finished.items()}
  # Rollback landed: blue is the fleet again, green drained, version 0.
  assert router.rollout.counters()["rollout_rollbacks"] == 1.0
  assert router.rollout.counters()["rollout_completed"] == 0.0
  assert router._fleet_version == 0
  assert router._version_weights is None
  assert all(router.health[i].state == "draining"
             for i in router.rollout._green)
  # Zero lost through the rollback — canary requests completed on
  # green IN PLACE (their streams differ from base; that is the
  # point of complete-in-place, not a defect).
  assert sorted(rolled) == sorted(range(len(prompts)))
  green_uids = {u for u, v in attr.items() if v == 1}
  assert green_uids, "the canary never carried traffic"
  for uid, toks in rolled.items():
    fin = router.finished[uid]
    assert fin.finish_reason == "length"
    if uid not in green_uids:
      np.testing.assert_array_equal(
          toks, base[uid],
          err_msg=f"blue req {uid} diverged from never-rolled fleet")
  # Both fleets admitted the identical request population.
  assert base_attr.keys() == attr.keys()
  router.close()


@pytest.mark.quick
def test_rollout_enabled_but_idle_is_bit_identical_zero_actuations():
  """The fault-free guard: rollout enabled but never invoked is
  bit-identical to the baseline fleet — zero actuations, zero version
  weights, no extra compiles, identical streams."""
  epl.init()
  prompts = _prompts(4)
  max_new = (6, 7, 4, 5)

  def drive(router):
    out = {}
    for i in range(2):
      assert router.submit(Request(uid=i, prompt=prompts[i],
                                   max_new_tokens=max_new[i]))
    for _ in range(2):
      for fin in router.step():
        out[fin.uid] = fin.tokens
    for i in range(2, 4):
      assert router.submit(Request(uid=i, prompt=prompts[i],
                                   max_new_tokens=max_new[i]))
    out.update(router.run())
    return out

  model, params = tiny_gpt()
  base_router = Router(model, params, num_replicas=2, num_slots=2,
                       prefill_chunk=4, registry=MetricRegistry())
  base = drive(base_router)
  base_router.close()
  slo_lib.reset()

  config = epl.Config({
      "serving": {"rollout": {"enabled": True}},
      "observability": {"slo": {"enabled": True, "ttft_p99_s": 100.0,
                                "itl_p99_s": 100.0}}})
  epl.init(config)
  router = Router(model, params, num_replicas=2, config=config,
                  num_slots=2, prefill_chunk=4,
                  registry=MetricRegistry())
  rolled = drive(router)
  monitor = slo_lib.get_monitor()
  assert monitor is not None and monitor.actuations == 0
  assert router.rollout is not None
  assert router.rollout.state == "idle"
  assert router.rollout.counters() == {
      "rollout_started": 0.0, "rollout_completed": 0.0,
      "rollout_rollbacks": 0.0, "rollout_spawn_failures": 0.0,
      "rollout_active": 0.0}
  assert router._version_weights is None and router._fleet_version == 0
  assert len(router.replicas) == 2
  for rep in router.replicas:
    assert rep.engine._step_fn._cache_size() == 1
    assert rep.engine._compile_sentinel.recompiles == 0
  assert sorted(base) == sorted(rolled)
  for uid in base:
    np.testing.assert_array_equal(rolled[uid], base[uid],
                                  err_msg=f"req {uid}")
  router.close()


# --------------------------------- slow: the chaos-rollout acceptance


@pytest.mark.slow
def test_midrollout_sigkill_of_blue_loses_nothing(tmp_path):
  """`make chaos-rollout` acceptance: SIGKILL one blue replica child
  mid-canary on a PROCESS-transport fleet — its requests fail over to
  the SURVIVING BLUE (never green: cross-version replay is fenced),
  zero requests are lost, every response is attributable to exactly
  one checkpoint version, the survivor's compile count stays 1, and
  the rollout still completes."""
  import signal

  events_path = str(tmp_path / "slo_events.jsonl")
  config = epl.Config({
      "serving": {
          "router": {"transport": "process", "heartbeat_s": 0.02,
                     "rpc_timeout_s": 60.0, "suspect_after": 0.5,
                     "down_after": 1.0},
          "rollout": {"enabled": True, "canary_frac": 0.5,
                      "canary_hold_s": 2.0, "min_replicas": 1,
                      "spawn_timeout_s": 300.0,
                      "drain_timeout_s": 120.0},
      },
      "observability": {"slo": {"enabled": True,
                                "events_path": events_path}},
  })
  epl.init(config)
  model, params = tiny_gpt()        # parent-side twin of the factory
  ckpt_dir = str(tmp_path / "green")
  save_checkpoint(ckpt_dir, params, step=11)
  router = Router(num_replicas=2, config=config, factory=FACTORY,
                  num_slots=4, prefill_chunk=4)
  prompts = _prompts(18, seed=21)
  max_new = 6
  admitted_version = {}
  uid_ctr = [0]

  def submit_one():
    uid = uid_ctr[0]
    if uid >= len(prompts):
      return
    if router.submit(Request(uid=uid, prompt=prompts[uid],
                             max_new_tokens=max_new)):
      admitted_version[uid] = router._replica_version(
          router.placement[uid])
    uid_ctr[0] += 1

  def pump(until, deadline_s=180.0):
    deadline = time.monotonic() + deadline_s
    while not until():
      assert time.monotonic() < deadline, (
          f"stuck in rollout state {router.rollout.state!r}, "
          f"states {router.states()}")
      submit_one()
      router.step()
      time.sleep(0.01)

  for _ in range(4):
    submit_one()
  router.step()
  assert router.rollout.begin(ckpt_dir) == 1
  pump(lambda: router.rollout.state == "canary")
  blue = list(router.rollout._blue)
  green = list(router.rollout._green)
  assert len(green) == 2
  # Load both blues, then SIGKILL one mid-flight.
  for _ in range(6):
    submit_one()
  router.step()
  victim = next(i for i in blue
                if router.replicas[i].has_work) if any(
      router.replicas[i].has_work for i in blue) else blue[0]
  pid = router.replicas[victim].child_pid
  os.kill(pid, signal.SIGKILL)
  survivor_blue = [i for i in blue if i != victim]
  pump(lambda: router.health[victim].state == "down" or
       not router.replicas[victim].has_work)
  # Drive to completion (breach-free canary -> cutover -> drain).
  pump(lambda: router.rollout.state == "idle")
  while uid_ctr[0] < len(prompts):
    submit_one()
    router.step()
  deadline = time.monotonic() + 120.0
  while router.has_work and time.monotonic() < deadline:
    router.step()
    time.sleep(0.01)
  # Zero lost: every ADMITTED request resolved exactly once; none
  # parked, none vanished.
  assert not router._parked
  for uid in admitted_version:
    fin = router.finished.get(uid)
    assert fin is not None, f"req {uid} lost"
    if fin.finish_reason == "shed":
      continue
    assert fin.finish_reason == "length"
    np.testing.assert_array_equal(
        fin.tokens, _oracle(model, params, prompts[uid], max_new),
        err_msg=f"req {uid}")
  for uid, ver in admitted_version.items():
    assert ver in (0, 1)
  # The surviving blue never recompiled while absorbing the failover.
  assert router.replicas[survivor_blue[0]].compile_count == 1
  assert router.rollout.counters()["rollout_completed"] == 1.0
  assert router._fleet_version == 1
  router.close()
  # Every transition landed in slo_events.jsonl as a rollout actuation.
  events = [json.loads(line) for line in open(events_path)]
  rollout_events = [e for e in events
                    if e.get("actuator") == "rollout"]
  assert all(e["event"] == "actuation" and e["rule"] == "rollout"
             for e in rollout_events)
  seen = {e["transition"] for e in rollout_events}
  assert {"begin", "green_up", "canary_start", "cutover",
          "completed"} <= seen
