"""Sequence/context parallelism tests: ring attention + Ulysses vs full
attention (new subsystem — no reference analog; SURVEY §5.7)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.sequence import ring_attention, ulysses_attention


def _full_attention(q, k, v, causal=True):
  B, S, H, D = q.shape
  scale = 1.0 / np.sqrt(D)
  scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
  if causal:
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
    scores = jnp.where(mask[None, None], scores, -1e30)
  probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
  return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _qkv(B=2, S=32, H=4, D=8, seed=0):
  r = np.random.RandomState(seed)
  mk = lambda: jnp.asarray(r.randn(B, S, H, D), jnp.float32)
  return mk(), mk(), mk()


def _seq_mesh(n=4):
  env = epl.init(epl.Config({"sequence.parallelism": "ring",
                             "sequence.axis_size": n}))
  return epl.current_plan().build_mesh()


@pytest.mark.quick
@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_full(causal):
  mesh = _seq_mesh(4)
  q, k, v = _qkv()
  out = jax.jit(lambda a, b, c: ring_attention(a, b, c, causal=causal))(
      q, k, v)
  ref = _full_attention(q, k, v, causal=causal)
  np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)


@pytest.mark.slow
def test_ring_grads_match_full():
  mesh = _seq_mesh(4)
  q, k, v = _qkv(seed=3)

  def loss_ring(q, k, v):
    return jnp.mean(ring_attention(q, k, v, causal=True) ** 2)

  def loss_full(q, k, v):
    return jnp.mean(_full_attention(q, k, v, causal=True) ** 2)

  g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
  g2 = jax.jit(jax.grad(loss_full, argnums=(0, 1, 2)))(q, k, v)
  for a, b in zip(g1, g2):
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_ring_explicit_blocks_off_mesh():
  epl.init()  # no seq axis; force 4 blocks — pure blockwise attention
  q, k, v = _qkv(seed=5)
  out = ring_attention(q, k, v, causal=True, num_blocks=4)
  ref = _full_attention(q, k, v, causal=True)
  np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)


def test_ring_indivisible_raises():
  epl.init()
  q, k, v = _qkv(S=30)
  with pytest.raises(ValueError):
    ring_attention(q, k, v, num_blocks=4)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_full(causal):
  mesh = _seq_mesh(4)
  q, k, v = _qkv()
  out = jax.jit(lambda a, b, c: ulysses_attention(a, b, c, causal=causal))(
      q, k, v)
  ref = _full_attention(q, k, v, causal=causal)
  np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)


def test_ulysses_head_divisibility():
  mesh = _seq_mesh(4)
  q, k, v = _qkv(H=6)  # 6 heads, seq axis 4 -> invalid
  with pytest.raises(ValueError):
    ulysses_attention(q, k, v)


@pytest.mark.slow
def test_gpt_with_ring_attention_matches_xla():
  from easyparallellibrary_tpu.models import GPT, GPTConfig
  env = epl.init(epl.Config({"sequence.parallelism": "ring",
                             "sequence.axis_size": 2}))
  mesh = epl.current_plan().build_mesh()
  base = dict(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
              d_ff=64, max_seq_len=16, dtype=jnp.float32, seq_parallel=True)
  ring_model = GPT(GPTConfig(**base, attn_impl="ring"))
  xla_model = GPT(GPTConfig(**base, attn_impl="xla"))
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4, 16)),
                    jnp.int32)
  params = ring_model.init(jax.random.PRNGKey(0), ids)["params"]
  out_ring = jax.jit(lambda p: ring_model.apply({"params": p}, ids))(params)
  out_xla = jax.jit(lambda p: xla_model.apply({"params": p}, ids))(params)
  np.testing.assert_allclose(out_ring, out_xla, rtol=2e-4, atol=2e-5)


def test_seq_sharded_batch_runs_on_seq_mesh():
  """End-to-end: activations actually sharded over the seq axis."""
  mesh = _seq_mesh(4)
  from jax.sharding import NamedSharding, PartitionSpec as P
  q, k, v = _qkv(B=2, S=32)
  qs = jax.device_put(q, NamedSharding(mesh, P("data", "seq", None, None)))
  ks = jax.device_put(k, NamedSharding(mesh, P("data", "seq", None, None)))
  vs = jax.device_put(v, NamedSharding(mesh, P("data", "seq", None, None)))
  out = jax.jit(lambda a, b, c: ring_attention(a, b, c))(qs, ks, vs)
  ref = _full_attention(q, k, v)
  np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)


@pytest.mark.slow
def test_seq_and_tensor_parallel_compose():
  """GPT on a seq2 x model2 x data2 mesh with ring attention + TP."""
  from easyparallellibrary_tpu.models import GPT, GPTConfig
  from easyparallellibrary_tpu.models.gpt import gpt_loss
  import optax
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state, make_train_step, parallelize)

  env = epl.init(epl.Config({"sequence.parallelism": "ring",
                             "sequence.axis_size": 2}))
  with epl.split(2):
    pass
  mesh = epl.current_plan().build_mesh()
  sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
  assert (sizes["seq"], sizes["model"], sizes["data"]) == (2, 2, 2)

  cfg = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                  d_ff=64, max_seq_len=16, dtype=jnp.float32,
                  tensor_parallel=True, seq_parallel=True, attn_impl="ring")
  model = GPT(cfg)
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4, 17)),
                    jnp.int32)
  tx = optax.adam(1e-2)

  def init_fn(rng):
    return TrainState.create(
        apply_fn=model.apply,
        params=model.init(rng, ids[:, :-1])["params"], tx=tx)

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  step = parallelize(
      make_train_step(lambda p, b, r: gpt_loss(model, p, b, r)),
      mesh, shardings)
  losses = []
  for _ in range(5):
    state, m = step(state, {"ids": ids}, jax.random.PRNGKey(1))
    losses.append(float(m["loss"]))
  assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_ring_block_size_config_finer_blocks():
  env = epl.init(epl.Config({"sequence.parallelism": "ring",
                             "sequence.axis_size": 2,
                             "sequence.block_size": 4}))
  epl.current_plan().build_mesh()
  q, k, v = _qkv(S=32, seed=7)   # 32/4 = 8 blocks (multiple of axis 2)
  out = jax.jit(lambda a, b, c: ring_attention(a, b, c, causal=True))(
      q, k, v)
  ref = _full_attention(q, k, v, causal=True)
  np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)


@pytest.mark.slow
def test_ring_default_uses_flash_shard_map(monkeypatch):
  """With an active seq axis and no block-size override, ring dispatches
  to the shard_map + flash-kernel path (the design point)."""
  import importlib
  ra_mod = importlib.import_module(
      "easyparallellibrary_tpu.sequence.ring_attention")
  mesh = _seq_mesh(4)
  called = {}
  orig = ra_mod._ring_flash

  def spy(q, k, v, causal):
    called["flash"] = True
    return orig(q, k, v, causal)

  monkeypatch.setattr(ra_mod, "_ring_flash", spy)
  q, k, v = _qkv(seed=11)
  ra_mod.ring_attention(q, k, v, causal=True)
  assert called.get("flash")


@pytest.mark.slow
@pytest.mark.parametrize("causal", [True, False])
def test_ring_einsum_impl_matches_flash(causal):
  """The two ring implementations (global-array einsum vs shard_map +
  flash kernel with recommunicating backward) agree on values AND
  gradients."""
  def run(impl):
    epl.init(epl.Config({"sequence.parallelism": "ring",
                         "sequence.axis_size": 4,
                         "sequence.ring_impl": impl}))
    epl.current_plan().build_mesh()
    q, k, v = _qkv(seed=13)

    def loss(q, k, v):
      return jnp.mean(ring_attention(q, k, v, causal=causal) ** 2)

    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, causal=causal))(
        q, k, v)
    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    return out, g

  out_f, g_f = run("flash")
  out_e, g_e = run("einsum")
  np.testing.assert_allclose(out_f, out_e, rtol=2e-5, atol=2e-6)
  for a, b in zip(g_f, g_e):
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_ring_flash_indivisible_seq_raises():
  _seq_mesh(4)
  q, k, v = _qkv(S=30)
  with pytest.raises(ValueError):
    ring_attention(q, k, v, causal=True)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_einsum_impl_matches_flash(causal):
  """Ulysses' two head-sharded attention implementations (pure GSPMD
  einsum vs shard_map + flash kernel) agree on values and gradients."""
  def run(impl):
    epl.init(epl.Config({"sequence.parallelism": "ulysses",
                         "sequence.axis_size": 4,
                         "sequence.ulysses_impl": impl}))
    epl.current_plan().build_mesh()
    q, k, v = _qkv(seed=17)

    def loss(q, k, v):
      return jnp.mean(ulysses_attention(q, k, v, causal=causal) ** 2)

    out = jax.jit(
        lambda a, b, c: ulysses_attention(a, b, c, causal=causal))(q, k, v)
    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    return out, g

  out_f, g_f = run("flash")
  out_e, g_e = run("einsum")
  np.testing.assert_allclose(out_f, out_e, rtol=2e-5, atol=2e-6)
  for a, b in zip(g_f, g_e):
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("n", [2, 4])
def test_zigzag_ring_matches_full(n):
  """Zigzag causal layout: values match full attention exactly (the
  layout exchange + balanced half-block schedule is numerics-neutral)."""
  epl.init(epl.Config({"sequence.parallelism": "ring",
                       "sequence.axis_size": n,
                       "sequence.ring_layout": "zigzag"}))
  epl.current_plan().build_mesh()
  q, k, v = _qkv(S=32, seed=21)
  out = jax.jit(lambda a, b, c: ring_attention(a, b, c, causal=True))(
      q, k, v)
  ref = _full_attention(q, k, v, causal=True)
  np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)


@pytest.mark.slow
def test_zigzag_ring_grads_match_full():
  epl.init(epl.Config({"sequence.parallelism": "ring",
                       "sequence.axis_size": 4,
                       "sequence.ring_layout": "zigzag"}))
  epl.current_plan().build_mesh()
  q, k, v = _qkv(S=32, seed=23)

  def loss_ring(q, k, v):
    return jnp.mean(ring_attention(q, k, v, causal=True) ** 2)

  def loss_full(q, k, v):
    return jnp.mean(_full_attention(q, k, v, causal=True) ** 2)

  g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
  g2 = jax.jit(jax.grad(loss_full, argnums=(0, 1, 2)))(q, k, v)
  for a, b in zip(g1, g2):
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_zigzag_noncausal_falls_back_to_contiguous():
  """Zigzag is causal-only; non-causal rings use the contiguous path
  (and still match full attention)."""
  epl.init(epl.Config({"sequence.parallelism": "ring",
                       "sequence.axis_size": 4,
                       "sequence.ring_layout": "zigzag"}))
  epl.current_plan().build_mesh()
  q, k, v = _qkv(S=32, seed=25)
  out = jax.jit(lambda a, b, c: ring_attention(a, b, c, causal=False))(
      q, k, v)
  ref = _full_attention(q, k, v, causal=False)
  np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)


@pytest.mark.slow
def test_unblockable_lengths_fall_back_to_einsum():
  """Sequence lengths with no power-of-two block divisor (e.g. 1030 =
  2*5*103 per device) must not raise or truncate: ring and Ulysses fall
  back to their einsum formulations, which have no blocking constraint."""
  from easyparallellibrary_tpu.kernels.flash_attention import (
      flash_blockable)
  assert not flash_blockable(515, d=8) and not flash_blockable(1030, d=8)
  assert flash_blockable(512, d=8) and flash_blockable(96, d=8)

  epl.init(epl.Config({"sequence.parallelism": "ring",
                       "sequence.axis_size": 2,
                       "sequence.ring_layout": "zigzag"}))
  epl.current_plan().build_mesh()
  # S=2060 -> per-device 1030 (even halves of 515, unblockable).
  q, k, v = _qkv(S=2060, H=2, D=8, seed=27)
  out = jax.jit(lambda a, b, c: ring_attention(a, b, c, causal=True))(
      q, k, v)
  ref = _full_attention(q, k, v, causal=True)
  np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                             rtol=2e-5, atol=2e-6)

  out_u = jax.jit(lambda a, b, c: ulysses_attention(a, b, c, causal=True))(
      q, k, v)
  np.testing.assert_allclose(np.asarray(out_u), np.asarray(ref),
                             rtol=2e-5, atol=2e-6)


@pytest.mark.slow
def test_dense_ring_matches_full_attention_both_layouts():
  """`sequence.ring_impl="dense"` (plain-XLA blocks — the pallas-free
  fallback and the compiled measurement path for the layout benchmarks)
  matches full attention, fwd and grad, under both causal layouts.
  Round-4 note: ring_layout now DEFAULTS to zigzag (1.65x compiled win,
  BASELINE.md)."""
  for layout in ("contiguous", "zigzag"):
    epl.init(epl.Config({"sequence.parallelism": "ring",
                         "sequence.axis_size": 8,
                         "sequence.ring_impl": "dense",
                         "sequence.ring_layout": layout}))
    epl.current_plan().build_mesh()
    B, S, H, D = 1, 128, 4, 16
    r = np.random.RandomState(0)
    q = jnp.asarray(r.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(r.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(r.randn(B, S, H, D), jnp.float32)

    def full(q):
      s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
      mask = jnp.tril(jnp.ones((S, S), bool))
      s = jnp.where(mask[None, None], s, -1e30)
      return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)

    out = jax.jit(lambda q: ring_attention(q, k, v, causal=True))(q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full(q)),
                               rtol=1e-4, atol=1e-5)
    g1 = jax.jit(jax.grad(
        lambda q: jnp.sum(ring_attention(q, k, v, causal=True) ** 2)))(q)
    g2 = jax.jit(jax.grad(lambda q: jnp.sum(full(q) ** 2)))(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-3, atol=1e-4)


def test_ring_layout_default_is_zigzag():
  assert epl.Config().sequence.ring_layout == "zigzag"
