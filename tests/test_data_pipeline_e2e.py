"""End-to-end input pipeline: native record reader -> batches ->
DevicePrefetcher -> fit (the full path the reference covers with its
dataset io tests + prefetch config)."""

import struct

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu import ops
from easyparallellibrary_tpu.io import (
    DevicePrefetcher, RecordReader, native_io_available, write_records)
from easyparallellibrary_tpu.parallel import (
    TrainState, create_sharded_train_state, make_train_step, parallelize)
from easyparallellibrary_tpu.runtime.loop import fit


def _write_token_files(tmp_path, n_files=4, recs_per_file=8, seq=16):
  """Each record: seq+1 int32 token ids."""
  r = np.random.RandomState(0)
  files = []
  for i in range(n_files):
    path = str(tmp_path / f"tokens_{i}.rec")
    recs = [r.randint(0, 64, seq + 1).astype(np.int32).tobytes()
            for _ in range(recs_per_file)]
    write_records(path, recs)
    files.append(path)
  return files


def _batches(files, batch_size=8, seq=16, use_native=True):
  """Generator: records -> fixed-size id batches (an epoch)."""
  def gen():
    buf = []
    for rec in RecordReader(files, use_native=use_native):
      buf.append(np.frombuffer(rec, np.int32).reshape(seq + 1))
      if len(buf) == batch_size:
        yield {"ids": np.stack(buf)}
        buf = []
  return gen


def test_native_reader_feeds_training(tmp_path):
  assert native_io_available()
  env = epl.init()
  mesh = epl.current_plan().build_mesh()
  files = _write_token_files(tmp_path)

  from easyparallellibrary_tpu.models import GPT, GPTConfig
  from easyparallellibrary_tpu.models.gpt import gpt_loss
  cfg = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                  d_ff=64, max_seq_len=16, dtype=jnp.float32)
  model = GPT(cfg)
  sample = jnp.zeros((8, 16), jnp.int32)

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, sample)["params"],
                             tx=optax.adam(1e-2))

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  step = parallelize(
      make_train_step(lambda p, b, r: gpt_loss(model, p, b, r)),
      mesh, shardings)

  make_epoch = _batches(files)
  # Data factory: fresh prefetcher per epoch (4 batches/epoch, 10 steps).
  data = lambda: DevicePrefetcher(make_epoch(), mesh, depth=2)
  state, metrics = fit(step, state, data, num_steps=10, log_every=0)
  assert int(state.step) == 10
  assert np.isfinite(float(metrics["loss"]))


def test_prefetcher_depth_and_order(tmp_path):
  env = epl.init()
  mesh = epl.current_plan().build_mesh()
  files = _write_token_files(tmp_path, n_files=2, recs_per_file=8)
  batches = list(_batches(files, batch_size=8)())
  pre = DevicePrefetcher(iter(batches), mesh, depth=2)
  got = [np.asarray(b["ids"]) for b in pre]
  assert len(got) == len(batches)
  for a, b in zip(got, batches):
    np.testing.assert_array_equal(a, b["ids"])
  # Leaves came back as global sharded arrays on the data axis.
  pre2 = DevicePrefetcher(iter(batches), mesh, depth=1)
  first = next(iter(pre2))
  assert "data" in str(first["ids"].sharding.spec)


def test_reader_skip_records_matches_slice(tmp_path):
  """skip_records=N yields exactly full_stream[N:] — the input-position
  resume contract — on both the native and python readers."""
  seq = 16
  files = _write_token_files(tmp_path, n_files=3, recs_per_file=5, seq=seq)
  full = list(RecordReader(files, use_native=False))
  assert len(full) == 15
  for use_native in ([True, False] if native_io_available() else [False]):
    for skip in (0, 1, 7, 14, 15, 20):
      got = list(RecordReader(files, use_native=use_native,
                              skip_records=skip))
      assert got == full[skip:], (use_native, skip)


def test_reader_skip_detects_truncation(tmp_path):
  """A payload cut short mid-record must raise the same IOError from the
  skip (seek) path as from the read path — a resume offset past a
  truncated file must not be swallowed as clean EOF (ADVICE r2)."""
  import pytest
  from easyparallellibrary_tpu.io.dataloader import _python_reader

  path = str(tmp_path / "trunc.rec")
  write_records(path, [b"x" * 32, b"y" * 32], use_native=False)
  with open(path, "r+b") as f:
    f.truncate(8 + 32 + 8 + 16)  # second payload half gone
  with pytest.raises(IOError, match="truncated record"):
    list(_python_reader([path], skip_records=0))
  with pytest.raises(IOError, match="truncated record"):
    list(_python_reader([path], skip_records=2))
