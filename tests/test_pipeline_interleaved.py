"""Megatron-interleaved 1F1B engine tests (VERDICT r3 item 3).

Covers: schedule-builder structure (ramp formula, dependency validation,
tick-global feed tables), numeric equivalence against the sequential
ground truth (even and uneven layer plans, multiple S/K/M), and the
config-dispatched path (pipeline.engine="smap" + pipeline_interleave).
Reference analog: the schedule family as core IP,
epl/strategies/scheduler.py:53-116 — this schedule is the one the
reference never had.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.models.gpt import (
    gpt_loss, make_gpt_smap_grad_fn)
from easyparallellibrary_tpu.parallel.pipeline_interleaved import (
    build_interleaved_schedule)


def test_schedule_ramp_formula():
  """Lockstep-tick interleaved ramp = 2(S-1) + (K-1)S one-chunk ticks
  (vs plain 1F1B's 2(S-1) ticks of K-chunk work — a strict bubble-work
  win for S > 2).  The builder re-validates every dependency/arrival
  internally; here we pin the tick count and the table invariants."""
  for S, K, M in [(2, 2, 4), (4, 2, 8), (4, 4, 8), (3, 2, 6)]:
    sch = build_interleaved_schedule(S, K, M)
    assert sch.T == M * K + 2 * (S - 1) + (K - 1) * S, (S, K, M, sch.T)
    # Every (virtual stage, micro-batch) pair runs exactly once per
    # direction.
    assert int(sch.f_valid.sum()) == S * K * M
    assert int(sch.b_valid.sum()) == S * K * M
    # Emits: one per micro-batch, on device S-1's final chunk.
    assert int(sch.emit_valid.sum()) == M
    assert sorted(sch.emit_mb[sch.emit_valid].tolist()) == list(range(M))
    # Tick-global feed table matches device 0's chunk-0 forwards.
    for t in range(sch.T):
      if sch.f_valid[t, 0] and sch.f_chunk[t, 0] == 0:
        assert sch.feed_mb[t] == sch.f_mb[t, 0]


def _run_pair(S, K, M, L, **kw):
  env = epl.init()
  mesh = env.cluster.build_mesh(stage=S)
  dp = mesh.devices.shape[list(mesh.axis_names).index("data")]
  base = dict(vocab_size=64, num_layers=L, num_heads=2, d_model=16,
              d_ff=32, max_seq_len=8, dtype=jnp.float32,
              pipeline_stages=S, num_micro_batch=M,
              pipeline_interleave=K, **kw)
  pp = GPT(GPTConfig(**base))
  ids = jnp.asarray(
      np.random.RandomState(0).randint(0, 64, (M * dp, 9)), jnp.int32)
  params = pp.init(jax.random.PRNGKey(0), ids[:, :-1])["params"]
  seq = GPT(GPTConfig(**base, pipeline_debug_sequential=True))

  grad_i = make_gpt_smap_grad_fn(pp, mesh)  # "1f1b" -> interleaved (K>1)
  (l1, _), g1 = jax.jit(lambda p: grad_i(p, {"ids": ids}, None))(params)
  l2, g2 = jax.jit(jax.value_and_grad(
      lambda p: gpt_loss(seq, p, {"ids": ids})[0]))(params)
  return l1, g1, l2, g2


@pytest.mark.parametrize("S,K,M,L", [(2, 2, 4, 8), (2, 3, 6, 6),
                                     (4, 2, 4, 8)])
@pytest.mark.slow
def test_interleaved_matches_sequential(S, K, M, L):
  l1, g1, l2, g2 = _run_pair(S, K, M, L)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=5e-3, atol=1e-5),
      g1, g2)


@pytest.mark.slow
def test_interleaved_uneven_layers_match_sequential():
  """6 layers over 4 virtual chunks: masked slots are real branches per
  device-chunk and numerics still match."""
  l1, g1, l2, g2 = _run_pair(2, 2, 4, 6)
  np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a.value if hasattr(a, "value") else a),
          np.asarray(b.value if hasattr(b, "value") else b),
          rtol=5e-3, atol=1e-5),
      g1, g2)


def test_interleaved_config_dispatch_trains():
  """pipeline.engine="smap" + pipeline_interleave=2 + PreferBackward
  dispatches the interleaved engine through make_gpt_train_step and the
  loss decreases."""
  import optax
  from easyparallellibrary_tpu.models.gpt import make_gpt_train_step
  from easyparallellibrary_tpu.parallel import (
      TrainState, create_sharded_train_state, parallelize)

  env = epl.init(epl.Config({"pipeline.engine": "smap"}))
  cfg = GPTConfig(vocab_size=64, num_layers=4, num_heads=2, d_model=16,
                  d_ff=32, max_seq_len=8, dtype=jnp.float32,
                  pipeline_stages=2, num_micro_batch=4,
                  pipeline_interleave=2)
  with epl.replicate(1):
    model = GPT(cfg)
  mesh = env.cluster.build_mesh(stage=2)
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (16, 9)),
                    jnp.int32)

  def init_fn(rng):
    return TrainState.create(apply_fn=model.apply,
                             params=model.init(rng, ids[:, :-1])["params"],
                             tx=optax.adam(1e-2))

  state, shardings = create_sharded_train_state(init_fn, mesh,
                                                jax.random.PRNGKey(0))
  step = parallelize(make_gpt_train_step(model), mesh, shardings)
  losses = []
  for i in range(4):
    state, m = step(state, {"ids": ids}, jax.random.PRNGKey(i))
    losses.append(float(m["loss"]))
  assert all(np.isfinite(l) for l in losses)
  assert losses[-1] < losses[0]


def test_interleaved_gpipe_order_raises():
  env = epl.init()
  mesh = env.cluster.build_mesh(stage=2)
  cfg = GPTConfig(vocab_size=64, num_layers=4, num_heads=2, d_model=16,
                  d_ff=32, max_seq_len=8, dtype=jnp.float32,
                  pipeline_stages=2, num_micro_batch=2,
                  pipeline_interleave=2)
  with pytest.raises(ValueError, match="interleave"):
    make_gpt_smap_grad_fn(GPT(cfg), mesh, schedule="gpipe")


@pytest.mark.parametrize("S,K,M", [(2, 2, 4), (4, 2, 8), (4, 4, 8),
                                   (3, 2, 6), (2, 3, 6), (8, 2, 8)])
def test_schedule_buffer_replay_no_collisions(S, K, M):
  """Replays the engine's exact buffer usage against the tick tables:
  every InBuf/Res/CotBuf read must see the value written for that
  (chunk, micro-batch), and no slot may be overwritten while its value
  is still pending — the mb % W slot keying is only collision-free
  while the in-flight window stays under W."""
  sch = build_interleaved_schedule(S, K, M)
  W = sch.W
  for d in range(S):
    inbuf = {}   # (chunk, slot) -> mb whose activation is stored
    res = {}
    cot = {}
    for t in range(sch.T):
      # receives (start of tick)
      if sch.rf_valid[t, d]:
        inbuf[(int(sch.rf_chunk[t, d]), int(sch.rf_slot[t, d]))] = \
            int(sch.f_mb[t - 1, (d - 1) % S])
      if sch.rb_valid[t, d]:
        cot[(int(sch.rb_chunk[t, d]), int(sch.rb_slot[t, d]))] = \
            int(sch.b_mb[t - 1, (d + 1) % S])
      # forward sub-tick: read input, write residual
      if sch.f_valid[t, d]:
        j, m = int(sch.f_chunk[t, d]), int(sch.f_mb[t, d])
        if not (j == 0 and d == 0):            # non-feed input from ring
          got = inbuf.get((j, m % W))
          assert got == m, (d, t, j, m, got)
        res[(j, m % W)] = m
      # emit writes the final-chunk cotangent on device S-1
      if sch.emit_valid[t] and d == S - 1:
        cot[(K - 1, int(sch.emit_mb[t]) % W)] = int(sch.emit_mb[t])
      # backward sub-tick: read cotangent + residual
      if sch.b_valid[t, d]:
        j, m = int(sch.b_chunk[t, d]), int(sch.b_mb[t, d])
        assert cot.get((j, m % W)) == m, (d, t, j, m)
        assert res.get((j, m % W)) == m, (d, t, j, m)


@pytest.mark.parametrize("S,K,M", [(2, 2, 2), (2, 3, 5), (3, 2, 7),
                                   (4, 2, 8), (4, 4, 6), (8, 2, 8)])
def test_interleaved_schedule_properties(S, K, M):
  """Host-side invariants of the list scheduler across an (S, K, M)
  grid: every (virtual stage, micro-batch) op runs exactly once in each
  direction, emits cover every micro-batch exactly once, the tick-global
  feed/fb tables agree with device 0's chunk-0 slots, and the buffer
  depth covers the in-flight window."""
  from easyparallellibrary_tpu.parallel.pipeline_interleaved import (
      build_interleaved_schedule)

  sched = build_interleaved_schedule(S, K, M)
  V = S * K
  # Each op exactly once per direction.
  assert int(sched.f_valid.sum()) == V * M
  assert int(sched.b_valid.sum()) == V * M
  for valid, chunk, mb in ((sched.f_valid, sched.f_chunk, sched.f_mb),
                           (sched.b_valid, sched.b_chunk, sched.b_mb)):
    seen = set()
    for t in range(sched.T):
      for d in range(S):
        if valid[t, d]:
          key = (int(chunk[t, d]) * S + d, int(mb[t, d]))
          assert key not in seen
          seen.add(key)
    assert len(seen) == V * M
  # Emits: every micro-batch exactly once.
  assert int(sched.emit_valid.sum()) == M
  assert sorted(sched.emit_mb[sched.emit_valid].tolist()) == list(range(M))
  # Tick-global feed table matches device 0's chunk-0 forward slots.
  for t in range(sched.T):
    if sched.f_valid[t, 0] and sched.f_chunk[t, 0] == 0:
      assert sched.feed_mb[t] == sched.f_mb[t, 0]
    if sched.b_valid[t, 0] and sched.b_chunk[t, 0] == 0:
      assert sched.fb_mb[t] == sched.b_mb[t, 0]
  # Buffer-slot collision freedom: replay the residual writes/reads —
  # a forward's (device, chunk, mb % W) slot must not be overwritten
  # by a later forward before its own backward reads it.
  open_slots = {}
  for t in range(sched.T):
    for d in range(S):
      if sched.f_valid[t, d]:
        key = (d, int(sched.f_chunk[t, d]),
               int(sched.f_mb[t, d]) % sched.W)
        assert key not in open_slots, (key, t)
        open_slots[key] = int(sched.f_mb[t, d])
      if sched.b_valid[t, d]:
        key = (d, int(sched.b_chunk[t, d]),
               int(sched.b_mb[t, d]) % sched.W)
        assert open_slots.get(key) == int(sched.b_mb[t, d]), (key, t)
        del open_slots[key]
  assert not open_slots
