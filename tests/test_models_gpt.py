"""GPT model family smoke + parallel-mode tests."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import easyparallellibrary_tpu as epl
from easyparallellibrary_tpu.models import GPT, GPTConfig
from easyparallellibrary_tpu.models.gpt import gpt_loss
from easyparallellibrary_tpu.parallel import (
    TrainState, create_sharded_train_state, make_train_step, parallelize)

TINY = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                 d_ff=64, max_seq_len=16, dtype=jnp.float32)


def _batch(b=8, s=16, vocab=64, seed=0):
  r = np.random.RandomState(seed)
  return {"ids": jnp.asarray(r.randint(0, vocab, (b, s + 1)), jnp.int32)}


def test_forward_shape():
  model = GPT(TINY)
  params = model.init(jax.random.PRNGKey(0),
                      jnp.zeros((2, 8), jnp.int32))["params"]
  logits = model.apply({"params": params}, jnp.zeros((2, 8), jnp.int32))
  assert logits.shape == (2, 8, 64)


def test_train_loss_decreases():
  epl.init()
  mesh = epl.current_plan().build_mesh()
  model = GPT(TINY)
  tx = optax.adam(1e-3)
  batch = _batch()

  def init_fn(rng):
    return TrainState.create(
        apply_fn=model.apply,
        params=model.init(rng, batch["ids"][:, :-1])["params"], tx=tx)

  state, shardings = create_sharded_train_state(
      init_fn, mesh, jax.random.PRNGKey(0))
  step = parallelize(
      make_train_step(lambda p, b, r: gpt_loss(model, p, b, r)),
      mesh, shardings)
  losses = []
  rng = jax.random.PRNGKey(1)
  for _ in range(10):
    state, m = step(state, batch, rng)
    losses.append(float(m["loss"]))
  assert losses[-1] < losses[0]
  assert losses[0] > 3.0  # ~ln(64) at init


def test_tensor_parallel_gpt_matches_dense():
  def run(tp):
    epl.init()
    cfg = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                    d_ff=64, max_seq_len=16, dtype=jnp.float32,
                    tensor_parallel=tp)
    if tp:
      with epl.split():
        pass
    mesh = epl.current_plan().build_mesh()
    model = GPT(cfg)
    batch = _batch()
    tx = optax.sgd(0.1)

    def init_fn(rng):
      return TrainState.create(
          apply_fn=model.apply,
          params=model.init(rng, batch["ids"][:, :-1])["params"], tx=tx)

    state, shardings = create_sharded_train_state(
        init_fn, mesh, jax.random.PRNGKey(5))
    step = parallelize(
        make_train_step(lambda p, b, r: gpt_loss(model, p, b, r)),
        mesh, shardings)
    losses = []
    for _ in range(3):
      state, m = step(state, batch, jax.random.PRNGKey(2))
      losses.append(float(m["loss"]))
    return losses

  np.testing.assert_allclose(run(True), run(False), rtol=2e-3)


@pytest.mark.slow
def test_remat_matches_no_remat():
  def run(remat):
    cfg = GPTConfig(vocab_size=64, num_layers=2, num_heads=4, d_model=32,
                    d_ff=64, max_seq_len=16, dtype=jnp.float32, remat=remat,
                    remat_policy="dots" if remat else "nothing")
    model = GPT(cfg)
    batch = _batch()
    params = model.init(jax.random.PRNGKey(0),
                        batch["ids"][:, :-1])["params"]
    loss, _ = gpt_loss(model, params, batch)
    grads = jax.grad(lambda p: gpt_loss(model, p, batch)[0])(params)
    return float(loss), grads

  l1, g1 = run(False)
  l2, g2 = run(True)
  np.testing.assert_allclose(l1, l2, rtol=1e-5)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
      g1, g2)


def test_dropout_train_eval_switch():
  import dataclasses
  cfg = dataclasses.replace(TINY, dropout_rate=0.5)
  model = GPT(cfg)
  ids = jnp.zeros((2, 8), jnp.int32)
  params = model.init(jax.random.PRNGKey(0), ids)["params"]
  # Training mode (deterministic=False): stochastic across rngs.
  o1 = model.apply({"params": params}, ids, deterministic=False,
                   rngs={"dropout": jax.random.PRNGKey(2)})
  o2 = model.apply({"params": params}, ids, deterministic=False,
                   rngs={"dropout": jax.random.PRNGKey(3)})
  assert float(jnp.max(jnp.abs(o1 - o2))) > 0
  # Eval default: deterministic, no dropout rng needed.
  e1 = model.apply({"params": params}, ids)
  e2 = model.apply({"params": params}, ids)
  np.testing.assert_allclose(e1, e2)
  from easyparallellibrary_tpu.models.gpt import gpt_loss
  # With an rng: training loss (dropout active, finite).
  l, _ = gpt_loss(model, params, {"ids": jnp.zeros((2, 9), jnp.int32)},
                  jax.random.PRNGKey(4))
  assert np.isfinite(float(l))
  # Without an rng: eval loss runs deterministically (no missing-rng
  # error) and differs from the dropout loss in general.
  l_eval, _ = gpt_loss(model, params, {"ids": jnp.zeros((2, 9), jnp.int32)})
  assert np.isfinite(float(l_eval))


def test_generate_greedy_and_sampled():
  from easyparallellibrary_tpu.models.gpt import generate
  model = GPT(TINY)
  prompt = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 4)),
                       jnp.int32)
  params = model.init(jax.random.PRNGKey(0), prompt)["params"]
  out = jax.jit(lambda p, ids: generate(model, p, ids, 6))(params, prompt)
  assert out.shape == (2, 10)
  np.testing.assert_array_equal(np.asarray(out[:, :4]), np.asarray(prompt))
  # Greedy is deterministic.
  out2 = jax.jit(lambda p, ids: generate(model, p, ids, 6))(params, prompt)
  np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
  # Sampling with different rngs differs (usually).
  s1 = generate(model, params, prompt, 6, temperature=1.0,
                rng=jax.random.PRNGKey(1))
  s2 = generate(model, params, prompt, 6, temperature=1.0,
                rng=jax.random.PRNGKey(2))
  assert not np.array_equal(np.asarray(s1), np.asarray(s2))
  import pytest
  with pytest.raises(ValueError):
    generate(model, params, jnp.zeros((1, 15), jnp.int32), 10)  # > max_seq


@pytest.mark.slow
def test_chunked_ce_matches_full_loss():
  """loss_chunk computes the identical loss/grads without materializing
  the [B, S, vocab] logits (round-1 NOTES bottleneck: vocab-32k head)."""
  from easyparallellibrary_tpu.models.gpt import gpt_loss
  base = dict(vocab_size=128, num_layers=2, num_heads=4, d_model=32,
              d_ff=64, max_seq_len=16, dtype=jnp.float32)
  full = GPT(GPTConfig(**base))
  chunked = GPT(GPTConfig(**base, loss_chunk=4))
  ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (4, 17)),
                    jnp.int32)
  params = full.init(jax.random.PRNGKey(0), ids[:, :-1])["params"]

  l_full, g_full = jax.jit(jax.value_and_grad(
      lambda p: gpt_loss(full, p, {"ids": ids})[0]))(params)
  l_chunk, g_chunk = jax.jit(jax.value_and_grad(
      lambda p: gpt_loss(chunked, p, {"ids": ids})[0]))(params)
  np.testing.assert_allclose(float(l_full), float(l_chunk), rtol=1e-6)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
      g_full, g_chunk)

  # The peak-memory reason to exist: grad-step temp bytes shrink.
  big = GPTConfig(**{**base, "vocab_size": 4096, "max_seq_len": 64})
  big_ids = jnp.asarray(np.random.RandomState(1).randint(0, 4096, (4, 65)),
                        jnp.int32)
  pf = GPT(big)
  params_big = pf.init(jax.random.PRNGKey(0), big_ids[:, :-1])["params"]

  def temp_of(cfg):
    m = GPT(cfg)
    f = jax.jit(jax.grad(lambda p: gpt_loss(m, p, {"ids": big_ids})[0]))
    return f.lower(params_big).compile().memory_analysis().temp_size_in_bytes

  t_full = temp_of(big)
  t_chunk = temp_of(GPTConfig(**{**big.__dict__, "loss_chunk": 8}))
  assert t_chunk < t_full, (t_chunk, t_full)


def test_generate_kv_cache_matches_full_forward():
  """The O(1)-per-token cached decode reproduces the full-forward path
  exactly (greedy and sampled) — VERDICT round-1 item 10."""
  from easyparallellibrary_tpu.models.gpt import generate
  model = GPT(TINY)
  prompt = jnp.asarray(np.random.RandomState(3).randint(0, 64, (2, 5)),
                       jnp.int32)
  params = model.init(jax.random.PRNGKey(0), prompt)["params"]

  cached = generate(model, params, prompt, 7)
  full = generate(model, params, prompt, 7, use_cache=False)
  np.testing.assert_array_equal(np.asarray(cached), np.asarray(full))

  rng = jax.random.PRNGKey(9)
  cached_s = generate(model, params, prompt, 7, temperature=0.8, rng=rng)
  full_s = generate(model, params, prompt, 7, temperature=0.8, rng=rng,
                    use_cache=False)
  np.testing.assert_array_equal(np.asarray(cached_s), np.asarray(full_s))

  # max_new_tokens=0 returns the prompt untouched on both paths.
  np.testing.assert_array_equal(
      np.asarray(generate(model, params, prompt, 0)), np.asarray(prompt))


def test_sample_logits_top_k_top_p():
  from easyparallellibrary_tpu.models.gpt import sample_logits
  rng = jax.random.PRNGKey(0)
  logits = jnp.asarray(np.random.RandomState(0).randn(64, 32), jnp.float32)
  greedy = jnp.argmax(logits, axis=-1)

  # temperature<=0 is greedy regardless of filters.
  np.testing.assert_array_equal(
      sample_logits(logits, rng, temperature=0.0, top_k=5), greedy)
  # top_k=1 collapses sampling to greedy at any temperature.
  np.testing.assert_array_equal(
      sample_logits(logits, rng, temperature=2.0, top_k=1), greedy)
  # tiny top_p keeps only the top token.
  np.testing.assert_array_equal(
      sample_logits(logits, rng, temperature=1.5, top_p=1e-6), greedy)
  # top_k=k: every sample lies inside the per-row top-k set.
  k = 4
  topk_sets = jax.lax.top_k(logits, k)[1]
  for seed in range(3):
    s = sample_logits(logits, jax.random.PRNGKey(seed), temperature=1.0,
                      top_k=k)
    assert bool(jnp.all(jnp.any(topk_sets == s[:, None], axis=-1)))
  # top_p=0.5 restricts support vs unfiltered sampling but stays valid.
  s = sample_logits(logits, rng, temperature=1.0, top_p=0.5)
  assert s.shape == (64,) and bool(jnp.all((s >= 0) & (s < 32)))


def test_generate_top_k_top_p_paths():
  from easyparallellibrary_tpu.models.gpt import generate
  epl.init()
  model = GPT(TINY)
  prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
  params = model.init(jax.random.PRNGKey(0), prompt)["params"]
  out = generate(model, params, prompt, 5, temperature=1.0, top_k=3,
                 top_p=0.9, rng=jax.random.PRNGKey(1))
  assert out.shape == (1, 8)
  # top_k=1 sampling equals greedy decoding.
  out_k1 = generate(model, params, prompt, 5, temperature=1.0, top_k=1,
                    rng=jax.random.PRNGKey(2))
  out_greedy = generate(model, params, prompt, 5)
  np.testing.assert_array_equal(out_k1, out_greedy)
  import pytest
  with pytest.raises(ValueError, match="top_p"):
    generate(model, params, prompt, 2, top_p=0.0)
  with pytest.raises(ValueError, match="top_k"):
    generate(model, params, prompt, 2, top_k=-1)


def test_moe_flops_accounts_for_top_k():
  from easyparallellibrary_tpu.models.gpt import gpt_flops_per_token
  base = dict(vocab_size=256, num_layers=4, num_heads=4, d_model=64,
              d_ff=256, max_seq_len=32)
  dense = gpt_flops_per_token(GPTConfig(**base))
  top1 = gpt_flops_per_token(GPTConfig(**base, num_experts=4, moe_top_k=1))
  top2 = gpt_flops_per_token(GPTConfig(**base, num_experts=4, moe_top_k=2))
  assert top1 == dense          # top-1 activates the same matmul count
  # moe_every=2 over 4 layers -> 2 MoE blocks; each adds one extra FFN.
  assert top2 == dense + 6.0 * 2 * (2 * 64 * 256)
